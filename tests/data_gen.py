"""Composable typed random data generators for differential tests.

Reference parity: integration_tests/src/main/python/data_gen.py (1282 LoC) —
the generator-driven breadth (nulls, NaN, ±0, extremes, skewed/repeating
keys, stable seeds) that powers the reference's entire correctness story.
This is an original implementation with the same contract: every generator
produces python values (None = null) plus a pyarrow type, specs compose into
tables, and every test that takes a seed is reproducible.
"""
from __future__ import annotations

import string
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

DEFAULT_NULL_PROB = 0.08
DEFAULT_SPECIAL_PROB = 0.05


class DataGen:
    """Base generator: draws specials with small probability, nulls with
    `null_prob` when nullable, otherwise delegates to `_gen_one`."""

    arrow_type: pa.DataType = pa.null()

    def __init__(self, nullable: bool = True,
                 null_prob: float = DEFAULT_NULL_PROB,
                 special_cases: Sequence = ()):
        self.nullable = nullable
        self.null_prob = null_prob if nullable else 0.0
        self.special_cases = list(special_cases)

    def with_special_case(self, value, weight: float = 1.0) -> "DataGen":
        self.special_cases.append(value)
        return self

    def _gen_one(self, rng: np.random.Generator):
        raise NotImplementedError

    def gen(self, rng: np.random.Generator):
        if self.null_prob and rng.random() < self.null_prob:
            return None
        if self.special_cases and rng.random() < DEFAULT_SPECIAL_PROB:
            return self.special_cases[int(rng.integers(0, len(self.special_cases)))]
        return self._gen_one(rng)

    def values(self, n: int, rng: np.random.Generator) -> list:
        return [self.gen(rng) for _ in range(n)]

    def __repr__(self):
        return type(self).__name__


class BooleanGen(DataGen):
    arrow_type = pa.bool_()

    def _gen_one(self, rng):
        return bool(rng.integers(0, 2))


class _IntGen(DataGen):
    _lo = -(1 << 31)
    _hi = (1 << 31) - 1
    arrow_type = pa.int32()

    def __init__(self, min_val: Optional[int] = None,
                 max_val: Optional[int] = None, **kw):
        self.min_val = self._lo if min_val is None else min_val
        self.max_val = self._hi if max_val is None else max_val
        specials = kw.pop("special_cases", None)
        if specials is None:
            specials = {self.min_val, self.max_val, 0, 1, -1}
            specials = sorted(v for v in specials
                              if self.min_val <= v <= self.max_val)
        super().__init__(special_cases=specials, **kw)

    def _gen_one(self, rng):
        return int(rng.integers(self.min_val, self.max_val, endpoint=True))


class ByteGen(_IntGen):
    _lo, _hi = -128, 127
    arrow_type = pa.int8()


class ShortGen(_IntGen):
    _lo, _hi = -(1 << 15), (1 << 15) - 1
    arrow_type = pa.int16()


class IntegerGen(_IntGen):
    arrow_type = pa.int32()


class LongGen(_IntGen):
    _lo, _hi = -(1 << 63), (1 << 63) - 1
    arrow_type = pa.int64()

    def _gen_one(self, rng):
        # sample via an unsigned offset so any [lo, hi] span up to the full
        # int64 range works (rng.integers alone can't span it inclusively)
        lo, hi = self.min_val, self.max_val
        span = hi - lo  # exact python int
        if span >= (1 << 64) - 1:
            return int(np.int64(rng.integers(0, 1 << 64, dtype=np.uint64)))
        return lo + int(rng.integers(0, span + 1, dtype=np.uint64))


class UniqueLongGen(DataGen):
    """Monotonically increasing values — never null, never repeats."""
    arrow_type = pa.int64()

    def __init__(self):
        super().__init__(nullable=False)
        self._next = 0

    def _gen_one(self, rng):
        self._next += 1
        return self._next


class _FloatGen(DataGen):
    arrow_type = pa.float32()
    _np = np.float32

    def __init__(self, min_val=None, max_val=None, no_nans: bool = False,
                 **kw):
        self.min_val = min_val
        self.max_val = max_val
        specials = kw.pop("special_cases", None)
        if specials is None:
            if min_val is None and max_val is None:
                info = np.finfo(self._np)
                specials = [0.0, -0.0, 1.0, -1.0,
                            float(info.max), float(info.min),
                            float(info.tiny), float("inf"), float("-inf")]
                if not no_nans:
                    specials.append(float("nan"))
            else:
                specials = []
        super().__init__(special_cases=specials, **kw)

    def _gen_one(self, rng):
        lo = -1e9 if self.min_val is None else self.min_val
        hi = 1e9 if self.max_val is None else self.max_val
        return float(self._np(rng.uniform(lo, hi)))


class FloatGen(_FloatGen):
    pass


class DoubleGen(_FloatGen):
    arrow_type = pa.float64()
    _np = np.float64


class StringGen(DataGen):
    """Random strings over an alphabet with length in [min_len, max_len].
    Specials: empty string, a space-padded token, a non-ascii token."""
    arrow_type = pa.string()

    def __init__(self, alphabet: str = string.ascii_letters + string.digits + " _",
                 min_len: int = 0, max_len: int = 20, ascii_only: bool = False,
                 **kw):
        self.alphabet = alphabet
        self.min_len = min_len
        self.max_len = max_len
        specials = kw.pop("special_cases", None)
        if specials is None:
            specials = ["", " ", "a" * max(1, max_len)]
            if not ascii_only:
                specials += ["é", "中文", "aéb"]
        super().__init__(special_cases=specials, **kw)

    def _gen_one(self, rng):
        n = int(rng.integers(self.min_len, self.max_len, endpoint=True))
        idx = rng.integers(0, len(self.alphabet), size=n)
        return "".join(self.alphabet[i] for i in idx)


class DecimalGen(DataGen):
    def __init__(self, precision: int = 10, scale: int = 2, **kw):
        import decimal
        self.precision = precision
        self.scale = scale
        self.arrow_type = pa.decimal128(precision, scale)
        lim = 10 ** precision - 1
        self._lim = lim
        specials = kw.pop("special_cases", None)
        if specials is None:
            specials = [decimal.Decimal(v).scaleb(-scale)
                        for v in (0, 1, -1, lim, -lim)]
        super().__init__(special_cases=specials, **kw)

    def _gen_one(self, rng):
        import decimal
        unscaled = int(rng.integers(-self._lim, self._lim, endpoint=True))
        return decimal.Decimal(unscaled).scaleb(-self.scale)


class DateGen(DataGen):
    """date32; default range 1940..2100 exercises pre-epoch negatives."""
    arrow_type = pa.date32()

    def __init__(self, min_days: int = -10957, max_days: int = 47482, **kw):
        self.min_days = min_days
        self.max_days = max_days
        super().__init__(special_cases=kw.pop("special_cases",
                                              [min_days, max_days, 0]), **kw)
        import datetime
        self.special_cases = [
            v if not isinstance(v, int)
            else datetime.date(1970, 1, 1) + datetime.timedelta(days=v)
            for v in self.special_cases]

    def _gen_one(self, rng):
        import datetime
        d = int(rng.integers(self.min_days, self.max_days, endpoint=True))
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=d)


class TimestampGen(DataGen):
    """timestamp[us]; default range ±2000 years of microseconds kept inside
    pandas/arrow-safe bounds (1678..2261)."""
    arrow_type = pa.timestamp("us")

    def __init__(self, min_us: int = -9_000_000_000_000_000,
                 max_us: int = 9_000_000_000_000_000, **kw):
        self.min_us = min_us
        self.max_us = max_us
        super().__init__(special_cases=kw.pop("special_cases",
                                              [min_us, max_us, 0]), **kw)
        import datetime
        epoch = datetime.datetime(1970, 1, 1)
        self.special_cases = [
            v if not isinstance(v, int)
            else epoch + datetime.timedelta(microseconds=v)
            for v in self.special_cases]

    def _gen_one(self, rng):
        import datetime
        us = int(rng.integers(self.min_us, self.max_us, endpoint=True))
        return datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=us)


class SetValuesGen(DataGen):
    """Uniformly picks from a fixed value set (None allowed in the set)."""

    def __init__(self, arrow_type, values: Sequence, **kw):
        self.arrow_type = arrow_type
        self._vals = list(values)
        super().__init__(nullable=None in self._vals, null_prob=0.0, **kw)

    def gen(self, rng):
        return self._vals[int(rng.integers(0, len(self._vals)))]


class RepeatSeqGen(DataGen):
    """Generates a fixed-length sequence from a child gen, then cycles it —
    the reference's way of making group/join keys that actually repeat."""

    def __init__(self, child: DataGen, length: int = 16):
        super().__init__(nullable=False, null_prob=0.0)
        self.child = child
        self.length = length
        self.arrow_type = child.arrow_type
        self._seq: Optional[list] = None
        self._i = 0

    def values(self, n, rng):
        seq = [self.child.gen(rng) for _ in range(self.length)]
        return [seq[i % self.length] for i in range(n)]

    def gen(self, rng):
        if self._seq is None:
            self._seq = [self.child.gen(rng) for _ in range(self.length)]
        v = self._seq[self._i % self.length]
        self._i += 1
        return v


class ArrayGen(DataGen):
    def __init__(self, child: DataGen, min_len: int = 0, max_len: int = 6,
                 **kw):
        self.child = child
        self.min_len = min_len
        self.max_len = max_len
        self.arrow_type = pa.list_(child.arrow_type)
        super().__init__(special_cases=kw.pop("special_cases", [[]]), **kw)

    def _gen_one(self, rng):
        n = int(rng.integers(self.min_len, self.max_len, endpoint=True))
        return [self.child.gen(rng) for _ in range(n)]


class StructGen(DataGen):
    def __init__(self, fields: Sequence[Tuple[str, DataGen]], **kw):
        self.fields = list(fields)
        self.arrow_type = pa.struct([pa.field(n, g.arrow_type)
                                     for n, g in self.fields])
        super().__init__(**kw)

    def _gen_one(self, rng):
        return {n: g.gen(rng) for n, g in self.fields}


class MapGen(DataGen):
    def __init__(self, key_gen: DataGen, value_gen: DataGen,
                 min_len: int = 0, max_len: int = 5, **kw):
        key_gen.null_prob = 0.0  # map keys may not be null
        self.key_gen = key_gen
        self.value_gen = value_gen
        self.min_len = min_len
        self.max_len = max_len
        self.arrow_type = pa.map_(key_gen.arrow_type, value_gen.arrow_type)
        super().__init__(**kw)

    def _gen_one(self, rng):
        n = int(rng.integers(self.min_len, self.max_len, endpoint=True))
        out, seen = [], set()
        for _ in range(n):
            k = self.key_gen.gen(rng)
            if k in seen or k is None:
                continue
            seen.add(k)
            out.append((k, self.value_gen.gen(rng)))
        return out


# -- common pre-built gen lists (reference: numeric_gens, all_basic_gens) ----

def byte_gen(): return ByteGen()
def short_gen(): return ShortGen()
def int_gen(): return IntegerGen()
def long_gen(): return LongGen()
def float_gen(): return FloatGen()
def double_gen(): return DoubleGen()
def string_gen(): return StringGen()
def boolean_gen(): return BooleanGen()
def date_gen(): return DateGen()
def timestamp_gen(): return TimestampGen()


def numeric_gens() -> List[DataGen]:
    return [ByteGen(), ShortGen(), IntegerGen(), LongGen(), FloatGen(),
            DoubleGen()]


def all_basic_gens() -> List[DataGen]:
    return numeric_gens() + [BooleanGen(), StringGen(), DateGen(),
                             TimestampGen()]


# -- table construction ------------------------------------------------------

def gen_table(spec: Sequence[Tuple[str, DataGen]], length: int = 2048,
              seed: int = 0) -> pa.Table:
    """spec: [(column_name, generator)] -> pyarrow Table with that schema."""
    rng = np.random.default_rng(seed)
    arrays, fields = [], []
    for name, g in spec:
        arrays.append(pa.array(g.values(length, rng), type=g.arrow_type))
        fields.append(pa.field(name, g.arrow_type))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def gen_df(session, spec, length: int = 2048, seed: int = 0,
           num_partitions: int = 1):
    """Generate a table and register it with the session as a DataFrame."""
    return session.create_dataframe(gen_table(spec, length, seed),
                                    num_partitions=num_partitions)
