"""Counting-sort compacted exchange (spark.rapids.shuffle.partitioning).

Differential coverage: the 'compact' path must produce byte-identical
per-partition contents to the legacy 'masked' path across hash /
round-robin / range exchanges, dict strings, nulls, masked inputs, and
n_out in {1, 3, 4, 8} — while the partitionDispatches /
partitionHostFetches metrics assert the O(1)-dispatch contract (ONE fused
counting-sort dispatch + ONE offsets fetch per input batch vs n_out each
on masked). Plus regression tests for the satellite fixes riding this PR
(catalyst DISTINCT/FILTER aggregates, ReusedExchangeExec, parser scope
push/pop, correlated NOT IN).
"""
import json
import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar.batch import to_arrow
from spark_rapids_tpu.expr.core import SparkException, col, lit
from spark_rapids_tpu.plan.nodes import bind_expr
from spark_rapids_tpu.plan.overrides import convert_plan
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.task import TaskContext
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSession

from asserts import assert_tpu_and_cpu_are_equal_collect
from data_gen import (
    DoubleGen, IntegerGen, LongGen, RepeatSeqGen, StringGen, gen_df,
    gen_table,
)


@pytest.fixture
def session():
    return TpuSession()


_SPEC = [("k", RepeatSeqGen(IntegerGen(min_val=0, max_val=40), length=30)),
         ("v", LongGen(min_val=-(1 << 40), max_val=1 << 40)),
         ("d", DoubleGen()),
         ("s", StringGen())]  # LongGen/DoubleGen/StringGen emit nulls


def _drain(ex, names):
    """Materialize an exchange: per-partition row lists (arrow pylist)."""
    parts = []
    for p in range(ex.num_partitions):
        rows = []
        with TaskContext(partition_id=p) as ctx:
            for b in ex.execute_partition(ctx, p):
                rows.extend(to_arrow(b, names).to_pylist())
        parts.append(rows)
    return parts


def _eq(a, b):
    """Order-SENSITIVE equality with NaN == NaN (floats gen NaNs)."""
    if isinstance(a, float) and isinstance(b, float):
        return (math.isnan(a) and math.isnan(b)) or a == b
    if isinstance(a, dict):
        return set(a) == set(b) and all(_eq(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(_eq(x, y) for x, y in zip(a, b))
    return a == b


def _build_exchange(partitioning, n_out, kind="hash", masked_input=False,
                    extra_conf=None):
    from spark_rapids_tpu.exec import tpu_nodes as X
    conf = {"spark.rapids.shuffle.partitioning": partitioning}
    conf.update(extra_conf or {})
    s = TpuSession(conf)
    df = gen_df(s, _SPEC, length=1500, seed=91, num_partitions=3)
    if masked_input:
        # FilterExec emits selection-mask batches: live rows at arbitrary
        # positions exercise the dead-row handling of the counting sort
        df = df.filter(col("v").is_not_null() | (col("k") < lit(20)))
    child, _ = convert_plan(df.plan, s.conf)
    if kind == "hash":
        ex = X.ShuffleExchangeExec(
            df.plan, [child], s.conf,
            [bind_expr(col("k"), df.plan.schema)], n_out=n_out)
    else:
        ex = X.RoundRobinExchangeExec(df.plan, [child], s.conf, n_out=n_out)
    return ex, list(df.plan.schema.names)


# Tier-1 keeps n_out=4 with masked input (the harder corner); the
# unmasked variant rides tools/slow_rehomed.txt (ci_check) since the
# round-18 headroom squeeze, and the degenerate (1), prime (3) and wide
# (8) fan-outs run under the full @slow/CI pass.
@pytest.mark.parametrize("n_out", [pytest.param(1, marks=pytest.mark.slow),
                                   pytest.param(3, marks=pytest.mark.slow),
                                   4,
                                   pytest.param(8, marks=pytest.mark.slow)])
@pytest.mark.parametrize("masked_input", [
    pytest.param(False, marks=pytest.mark.slow), True])
def test_hash_exchange_compact_matches_masked(n_out, masked_input):
    exc, names = _build_exchange("compact", n_out, masked_input=masked_input)
    exm, _ = _build_exchange("masked", n_out, masked_input=masked_input)
    got_c = _drain(exc, names)
    got_m = _drain(exm, names)
    # contents AND row order per partition match: the counting sort is
    # stable, so each partition sees its rows in input order, exactly as
    # the mask slices do
    assert _eq(got_c, got_m)
    total = sum(len(p) for p in got_c)
    assert total == sum(len(p) for p in got_m)
    # row conservation via the new metrics counters
    assert exc.metrics.metric(M.NUM_OUTPUT_ROWS).value == total
    assert exm.metrics.metric(M.NUM_OUTPUT_ROWS).value == total


@pytest.mark.parametrize("n_out", [3, 8])
def test_round_robin_exchange_compact_matches_masked(n_out):
    exc, names = _build_exchange("compact", n_out, kind="rr")
    exm, _ = _build_exchange("masked", n_out, kind="rr")
    got_c = _drain(exc, names)
    got_m = _drain(exm, names)
    assert _eq(got_c, got_m)
    assert exc.metrics.metric(M.NUM_OUTPUT_ROWS).value == \
        sum(len(p) for p in got_c)


def test_dict_string_keys_compact_matches_masked():
    """Hash exchange keyed ON a dict-encoded string column."""
    from spark_rapids_tpu.exec import tpu_nodes as X
    spec = [("s", RepeatSeqGen(StringGen(nullable=False), length=13)),
            ("v", LongGen())]
    out = {}
    for partitioning in ("compact", "masked"):
        s = TpuSession({"spark.rapids.shuffle.partitioning": partitioning})
        df = gen_df(s, spec, length=900, seed=97, num_partitions=3)
        child, _ = convert_plan(df.plan, s.conf)
        ex = X.ShuffleExchangeExec(
            df.plan, [child], s.conf,
            [bind_expr(col("s"), df.plan.schema)], n_out=4)
        out[partitioning] = _drain(ex, list(df.plan.schema.names))
    assert _eq(out["compact"], out["masked"])


def test_nested_columns_compact_matches_masked():
    """Array/struct payload columns ride the permuting gather (masked
    shares planes; compact must rebuild offsets + children correctly)."""
    from spark_rapids_tpu.exec import tpu_nodes as X
    t = pa.table({
        "k": pa.array([i % 9 for i in range(300)], pa.int64()),
        "a": pa.array([[i, i + 1] if i % 4 else None for i in range(300)],
                      pa.list_(pa.int32())),
        "st": pa.array([{"x": i} if i % 5 else None for i in range(300)],
                       pa.struct([("x", pa.int64())])),
    })
    out = {}
    for mode in ("compact", "masked"):
        s = TpuSession({"spark.rapids.shuffle.partitioning": mode})
        df = s.create_dataframe(t, num_partitions=3)
        child, _ = convert_plan(df.plan, s.conf)
        ex = X.ShuffleExchangeExec(
            df.plan, [child], s.conf,
            [bind_expr(col("k"), df.plan.schema)], n_out=4)
        out[mode] = _drain(ex, ["k", "a", "st"])
    assert _eq(out["compact"], out["masked"])


def test_compact_metrics_single_dispatch_single_fetch():
    """THE acceptance assertion: per input batch, the compact path issues
    exactly ONE partition-kernel dispatch and ONE host offsets fetch; the
    masked path pays n_out of each."""
    n_out = 4
    for partitioning, per_batch in (("compact", 1), ("masked", n_out)):
        ex, _ = _build_exchange(partitioning, n_out)
        ex._materialize()
        n_in = 3  # one batch per source partition
        assert ex.metrics.metric(M.PARTITION_DISPATCHES).value \
            == n_in * per_batch
        assert ex.metrics.metric(M.PARTITION_HOST_FETCHES).value \
            == n_in * per_batch


def test_compact_outputs_are_right_sized():
    """Compact sub-batches carry no selection mask, have host-int row
    counts (no deferred count syncs), and capacity sized by actual rows
    instead of the input capacity."""
    from spark_rapids_tpu.columnar.batch import LazyRowCount, round_capacity
    ex, _ = _build_exchange("compact", 4, masked_input=True)
    with TaskContext(partition_id=0) as ctx:
        for b in ex.execute_partition(ctx, 0):
            assert b.row_mask is None
            assert not isinstance(b.num_rows, LazyRowCount)
            assert b.capacity == round_capacity(int(b.num_rows))


@pytest.mark.parametrize("partitioning", ["compact", "masked"])
def test_group_by_differential_under_partitioning(partitioning):
    s = TpuSession({"spark.rapids.shuffle.partitioning": partitioning})
    assert_tpu_and_cpu_are_equal_collect(
        lambda ss: gen_df(ss, _SPEC, length=2000, seed=67, num_partitions=4)
        .group_by(col("k")).agg(F.sum("v").alias("sv"),
                                F.count().alias("n"),
                                F.min("d").alias("md")),
        s, ignore_order=True)


@pytest.mark.parametrize("partitioning", ["compact", "masked"])
def test_range_exchange_global_sort_differential(partitioning):
    s = TpuSession({"spark.rapids.shuffle.partitioning": partitioning})
    spec = [("a", IntegerGen(min_val=-500, max_val=500)),
            ("b", LongGen(min_val=0, max_val=1 << 30))]
    assert_tpu_and_cpu_are_equal_collect(
        lambda ss: gen_df(ss, spec, length=3000, seed=79, num_partitions=4)
        .order_by(col("a").asc_nulls_first(), col("b").desc()),
        s)


def test_range_exchange_compact_metrics(session):
    """The range exchange rides the same counting-sort tail."""
    from spark_rapids_tpu.exec import tpu_nodes as X
    df = session.create_dataframe(
        pa.table({"a": pa.array(np.arange(200)[::-1])}),
        num_partitions=4).order_by(col("a"))
    root, _ = convert_plan(df.plan, session.conf)
    assert isinstance(root, X.SortExec)
    ex = root.children[0]
    assert isinstance(ex, X.RangeExchangeExec)
    ex._materialize()
    assert ex.metrics.metric(M.PARTITION_DISPATCHES).value == 4  # 1/batch
    assert ex.metrics.metric(M.PARTITION_HOST_FETCHES).value == 4


def test_serialized_mode_uses_compact_partitioning():
    """SERIALIZED shuffle serializes straight from the sorted planes —
    no per-sub-batch compaction pass."""
    s = TpuSession({"spark.rapids.shuffle.mode": "SERIALIZED",
                    "spark.rapids.shuffle.compression.codec": "zlib",
                    "spark.rapids.shuffle.partitioning": "compact"})
    assert_tpu_and_cpu_are_equal_collect(
        lambda ss: gen_df(ss, _SPEC, length=1200, seed=71, num_partitions=3)
        .group_by(col("k")).agg(F.sum("v").alias("sv"),
                                F.count().alias("n")),
        s, ignore_order=True)


def test_partitioning_conf_rejects_unknown_value():
    ex, _ = _build_exchange("compact", 2)
    ex.conf.set(C.SHUFFLE_PARTITIONING, "bogus")
    with pytest.raises(ValueError, match="compact.*masked|masked.*compact"):
        ex._materialize()


# ---------------------------------------------------------------------------
# Satellite regressions
# ---------------------------------------------------------------------------

def _agg_plan_json(is_distinct=False, filter_idx=None):
    """Minimal Catalyst TreeNode JSON: LocalTableScan-less aggregate shape
    is overkill; reuse the golden count_star plan and mutate the
    AggregateExpression node."""
    import os
    golden = os.path.join(os.path.dirname(__file__), "golden_plans",
                          "count_star.json")
    with open(golden) as f:
        arr = json.load(f)
    for node in arr:
        s = json.dumps(node)
        if "AggregateExpression" not in s:
            continue
        for row in node.get("aggregateExpressions", []):
            for sub in row:
                if sub.get("class", "").endswith("AggregateExpression"):
                    sub["isDistinct"] = is_distinct
                    if filter_idx is not None:
                        sub["filter"] = filter_idx
    return json.dumps(arr)


def test_catalyst_rejects_distinct_aggregate(session, tmp_path):
    from spark_rapids_tpu.plan.catalyst import ingest_catalyst
    raw = _agg_plan_json(is_distinct=True).replace("$DATA", str(tmp_path))
    with pytest.raises(SparkException, match="isDistinct"):
        ingest_catalyst(raw, session)


def test_catalyst_rejects_filtered_aggregate(session, tmp_path):
    from spark_rapids_tpu.plan.catalyst import ingest_catalyst
    raw = _agg_plan_json(filter_idx=1).replace("$DATA", str(tmp_path))
    with pytest.raises(SparkException, match="filter|FILTER"):
        ingest_catalyst(raw, session)


def test_catalyst_rejects_reused_exchange(session):
    from spark_rapids_tpu.plan.catalyst import ingest_catalyst
    bad = [{"class": "org.apache.spark.sql.execution.exchange."
            "ReusedExchangeExec", "num-children": 0}]
    # previously died with IndexError unwrapping a nonexistent child
    with pytest.raises(SparkException, match="ReusedExchangeExec"):
        ingest_catalyst(json.dumps(bad), session)


@pytest.fixture
def scoped_session():
    s = TpuSession()
    s.create_or_replace_temp_view("x", s.create_dataframe(
        {"k": [1, 2, 3, 4], "v": [10, 20, 30, 40]}))
    s.create_or_replace_temp_view("y", s.create_dataframe(
        {"k": [1, 2, 3], "w": [7, 8, 9]}))
    s.create_or_replace_temp_view("z", s.create_dataframe(
        {"k": [10, 30], "g": [1, 3]}))
    return s


def test_derived_table_keeps_outer_aliases(scoped_session):
    """FROM x JOIN (SELECT ...) d: parsing the derived table must not drop
    the alias `x` from the correlation scope (it previously rebound
    self._scope, so the EXISTS below failed to resolve x.v)."""
    s = scoped_session
    got = s.sql(
        "SELECT x.k FROM x JOIN (SELECT k FROM y) d ON x.k = d.k "
        "WHERE EXISTS (SELECT 1 FROM z WHERE z.k = x.v)").to_pydict()
    assert sorted(got["k"]) == [1, 3]


def test_derived_table_inner_alias_does_not_leak(scoped_session):
    """The derived table's inner alias `y` must NOT be visible to the
    outer correlation scope after the nested parse pops it."""
    s = scoped_session
    with pytest.raises(SparkException, match="cannot resolve"):
        s.sql("SELECT x.k FROM x JOIN (SELECT k FROM y) d ON x.k = d.k "
              "WHERE EXISTS (SELECT 1 FROM z WHERE z.k = y.w)").collect()


def test_correlated_not_in_rejected(scoped_session):
    """The whole-subquery has-null shortcut is unsound under correlation;
    correlated NOT IN now rejects instead of over-dropping rows."""
    s = scoped_session
    with pytest.raises(SparkException, match="correlated NOT IN"):
        s.sql("SELECT k FROM x WHERE k NOT IN "
              "(SELECT k FROM z WHERE z.g = x.k)").collect()


def test_uncorrelated_not_in_still_works(scoped_session):
    s = scoped_session
    got = s.sql("SELECT k FROM x WHERE k NOT IN (SELECT g FROM z)"
                ).to_pydict()
    assert sorted(got["k"]) == [2, 4]
