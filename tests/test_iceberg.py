"""Iceberg v1-subset table format tests: spec-shaped metadata/manifest
layout, snapshot replay, time travel, optimistic commits (reference
sql-plugin iceberg/ integration scope)."""
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql.iceberg import (
    IcebergConcurrentCommit, IcebergTable)
from spark_rapids_tpu.io.avro import read_avro
from spark_rapids_tpu.expr.core import col, lit


@pytest.fixture
def session():
    return TpuSession()


def _t(k, v):
    return pa.table({"k": pa.array(k, pa.int64()),
                     "v": pa.array(v, pa.float64())})


def test_create_layout_and_read(session, tmp_path):
    p = str(tmp_path / "ice")
    t = IcebergTable.create(session, p, _t([1, 2, 3], [1., 2., 3.]))
    # spec-shaped layout: version hint, metadata json, manifest-list +
    # manifest avro, data parquet
    assert open(os.path.join(p, "metadata", "version-hint.text")).read() \
        == "1"
    meta = json.load(open(os.path.join(p, "metadata", "v1.metadata.json")))
    assert meta["format-version"] == 1
    assert meta["schema"]["fields"][0]["name"] == "k"
    snap = meta["snapshots"][0]
    ml = read_avro(os.path.join(p, snap["manifest-list"])).to_pylist()
    assert ml[0]["added_data_files_count"] == 1
    manifest = read_avro(os.path.join(p, ml[0]["manifest_path"]))
    entry = manifest.to_pylist()[0]
    assert entry["status"] == 1
    assert entry["data_file"]["file_format"] == "PARQUET"
    assert entry["data_file"]["record_count"] == 3
    got = IcebergTable.for_path(session, p).to_df().collect().to_pylist()
    assert sorted(r["k"] for r in got) == [1, 2, 3]


def test_append_and_time_travel(session, tmp_path):
    p = str(tmp_path / "ice")
    t = IcebergTable.create(session, p, _t([1], [1.0]))
    s0 = t.snapshots()[0]["snapshot_id"]
    t.append(session.create_dataframe(_t([2], [2.0])))
    t.append(session.create_dataframe(_t([3], [3.0])))
    assert t.to_df().count() == 3
    snaps = t.snapshots()
    assert len(snaps) == 3
    assert t.to_df(snapshot_id=s0).count() == 1
    assert t.to_df(snapshot_id=snaps[1]["snapshot_id"]).count() == 2
    # a fresh reader sees the same state
    assert IcebergTable.for_path(session, p).to_df().count() == 3


def test_engine_queries_over_iceberg(session, tmp_path):
    p = str(tmp_path / "ice")
    rng = np.random.default_rng(4)
    t = IcebergTable.create(
        session, p, _t(rng.integers(0, 10, 500).tolist(),
                       rng.uniform(0, 5, 500).tolist()))
    from spark_rapids_tpu.sql import functions as F
    out = (t.to_df().filter(col("v") > lit(1.0)).group_by("k")
           .agg(F.sum(col("v")).alias("sv")).count())
    assert out <= 10


def test_optimistic_commit_conflict(session, tmp_path):
    p = str(tmp_path / "ice")
    IcebergTable.create(session, p, _t([1], [1.0]))
    a = IcebergTable.for_path(session, p)
    b = IcebergTable.for_path(session, p)
    a.append(session.create_dataframe(_t([2], [2.0])))
    # b still believes version 1 is current; its commit must conflict
    meta = b._metadata(1)
    with pytest.raises(IcebergConcurrentCommit):
        b._commit_metadata(2, meta)


def test_nested_datetime_in_avro_roundtrip():
    # nested struct timestamp/date fields encode as epoch ints (review
    # regression: as_py() datetimes used to crash enc_val)
    import datetime as dt
    import tempfile
    from spark_rapids_tpu.io.avro import read_avro, write_avro
    t = pa.table({"s": pa.array(
        [{"ts": dt.datetime(2024, 5, 1, 12, 30), "d": dt.date(2024, 5, 1)},
         None],
        pa.struct([("ts", pa.timestamp("us")), ("d", pa.date32())]))})
    p = os.path.join(tempfile.mkdtemp(), "x.avro")
    write_avro(p, t)
    assert read_avro(p).to_pylist() == t.to_pylist()
