"""Adaptive query execution parity + decision suite (exec/adaptive.py,
plan/cost.py measured hints).

The contract under test: every adaptive replan is INVISIBLE in results
(broadcast-converted joins match the shuffled plan row-for-row after
canonical ordering; skew splits match it byte-for-byte WITHOUT
reordering) and VISIBLE everywhere else (last_aqe(), EXPLAIN ANALYZE,
the rapids_aqe_* counters, the history record's aqe field).
"""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession

from asserts import assert_tables_equal, assert_tpu_and_cpu_are_equal_collect

#: broadcastRowThreshold=1 defeats the static small-estimate broadcast,
#: so the planner takes the shuffled branch — exactly where the adaptive
#: node measures the build side and converts back
AQE_ON = {"spark.rapids.sql.join.broadcastRowThreshold": 1}
AQE_OFF = {"spark.rapids.sql.join.broadcastRowThreshold": 1,
           "spark.rapids.sql.adaptive.enabled": "false"}


def _sides(n=60, seed=5, skew=None):
    rng = np.random.default_rng(seed)
    if skew is None:
        lk = [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(0, 12, n)]
    else:
        lk = [0 if rng.random() < skew else int(x)
              for x in rng.integers(0, 12, n)]
    left = pa.table({
        "k": pa.array(lk, pa.int64()),
        "lv": pa.array(rng.integers(0, 100, n).astype(np.int64)),
    })
    right = pa.table({
        "k": pa.array([None if rng.random() < 0.1 else int(x)
                       for x in rng.integers(0, 15, n // 2)], pa.int64()),
        "rv": pa.array(rng.uniform(0, 1, n // 2)),
    })
    return left, right


def _join(s, left_t, right_t, how="inner", parts=(3, 2)):
    return s.create_dataframe(left_t, num_partitions=parts[0]).join(
        s.create_dataframe(right_t, num_partitions=parts[1]),
        on="k", how=how)


def _find_execs(root, name):
    """All exec nodes of class `name`, following adaptive nodes into
    their runtime-chosen subtree."""
    out = []

    def walk(n):
        if type(n).__name__ == name:
            out.append(n)
        chosen = getattr(n, "_chosen", None)
        if chosen is not None:
            walk(chosen)
        for c in getattr(n, "children", []):
            walk(c)

    walk(root)
    return out


def _decisions(sess, kind=None):
    doc = sess.last_aqe()
    ds = (doc or {}).get("decisions", [])
    return [d for d in ds if kind is None or d["kind"] == kind]


# ---------------------------------------------------------------------------
# shuffle-hash -> broadcast conversion
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_broadcast_conversion_matches_shuffled(how):
    """The converted plan and the static shuffled plan agree row-for-row
    (conversion reorders rows across partitions, so canonical order)."""
    left_t, right_t = _sides()
    on = TpuSession(AQE_ON)
    off = TpuSession(AQE_OFF)
    t_on = _join(on, left_t, right_t, how).collect()
    t_off = _join(off, left_t, right_t, how).collect()
    assert_tables_equal(t_on, t_off, ignore_order=True)
    assert _decisions(on, "broadcast_conversion"), \
        f"no conversion decision: {on.last_aqe()!r}"
    assert not _decisions(off), "decisions recorded with adaptive off"


@pytest.mark.parametrize("scenario", ["ansi", "masked", "empty", "skewed"])
def test_broadcast_conversion_parity_scenarios(scenario):
    conf = dict(AQE_ON)
    skew = None
    if scenario == "ansi":
        conf["spark.sql.ansi.enabled"] = "true"
    elif scenario == "masked":
        conf["spark.rapids.shuffle.partitioning"] = "masked"
    elif scenario == "skewed":
        skew = 0.7
    left_t, right_t = _sides(80, seed=11, skew=skew)
    if scenario == "empty":
        right_t = right_t.slice(0, 0)
    on = TpuSession(conf)
    off_conf = dict(conf)
    off_conf["spark.rapids.sql.adaptive.enabled"] = "false"
    off = TpuSession(off_conf)
    t_on = _join(on, left_t, right_t, "inner").collect()
    t_off = _join(off, left_t, right_t, "inner").collect()
    assert_tables_equal(t_on, t_off, ignore_order=True)
    # and both agree with the independent CPU backend
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _join(s, left_t, right_t, "inner"),
        TpuSession(conf), ignore_order=True)


def test_conversion_chooses_broadcast_and_saves_dispatches():
    left_t, right_t = _sides()
    s = TpuSession(AQE_ON)
    _join(s, left_t, right_t).collect()
    root = s._last_exec
    adaptive = _find_execs(root, "AdaptiveShuffledHashJoinExec")
    assert adaptive, "planner did not place the adaptive join node"
    assert type(adaptive[0]._chosen).__name__ == "BroadcastHashJoinExec"
    (d,) = _decisions(s, "broadcast_conversion")
    assert d["build_bytes"] <= d["threshold_bytes"]
    assert d["dispatches_saved"] >= 1
    assert s.last_aqe()["dispatches_saved"] >= 1


def test_over_threshold_stays_shuffled():
    left_t, right_t = _sides()
    conf = dict(AQE_ON)
    conf["spark.rapids.sql.adaptive.broadcastThresholdBytes"] = 8
    s = TpuSession(conf)
    t = _join(s, left_t, right_t).collect()
    adaptive = _find_execs(s._last_exec, "AdaptiveShuffledHashJoinExec")
    assert adaptive
    assert type(adaptive[0]._chosen).__name__ == "ShuffledHashJoinExec"
    assert not _decisions(s, "broadcast_conversion")
    off = TpuSession(AQE_OFF)
    assert_tables_equal(t, _join(off, left_t, right_t).collect(),
                        ignore_order=True)


@pytest.mark.parametrize("how", ["right", "full"])
def test_right_and_full_never_convert(how):
    """right/full track probe matches across the whole build — they must
    keep the shuffled plan (and still match it exactly)."""
    left_t, right_t = _sides()
    on = TpuSession(AQE_ON)
    t_on = _join(on, left_t, right_t, how).collect()
    assert not _decisions(on, "broadcast_conversion")
    off = TpuSession(AQE_OFF)
    assert_tables_equal(t_on, _join(off, left_t, right_t, how).collect(),
                        ignore_order=True)


def test_conversion_decisions_deterministic():
    """Same query, same conf -> byte-identical decision docs (the golden
    regeneration contract: adaptive plans must reproduce)."""
    left_t, right_t = _sides()
    docs = []
    for _ in range(2):
        s = TpuSession(AQE_ON)
        _join(s, left_t, right_t).collect()
        docs.append(s.last_aqe())
    assert docs[0] == docs[1]


# ---------------------------------------------------------------------------
# skewed-partition split
# ---------------------------------------------------------------------------

#: conversion disabled (threshold 0) so ONLY the skew splitter is live;
#: split slices are in-order, so results must match WITHOUT reordering
SKEW_CONF = {"spark.rapids.sql.join.broadcastRowThreshold": 1,
             "spark.rapids.sql.adaptive.broadcastThresholdBytes": 0,
             "spark.rapids.sql.adaptive.skewFactor": 1.5}


def test_skew_split_rejoins_in_order():
    left_t, right_t = _sides(600, seed=3, skew=0.8)
    on = TpuSession(SKEW_CONF)
    t_on = _join(on, left_t, right_t, parts=(3, 3)).collect()
    splits = _decisions(on, "skew_split")
    assert splits, f"skew never split: {on.last_aqe()!r}"
    assert all(d["splits"] >= 2 and d["rows"] > d["threshold_rows"]
               for d in splits)
    off = TpuSession(AQE_OFF)
    t_off = _join(off, left_t, right_t, parts=(3, 3)).collect()
    # NO ignore_order: sub-batches must rejoin in the exact order the
    # unsplit partition would have produced
    assert_tables_equal(t_on, t_off)


def test_skew_factor_zero_disables_split():
    left_t, right_t = _sides(600, seed=3, skew=0.8)
    conf = dict(SKEW_CONF)
    conf["spark.rapids.sql.adaptive.skewFactor"] = 0
    s = TpuSession(conf)
    _join(s, left_t, right_t, parts=(3, 3)).collect()
    assert not _decisions(s, "skew_split")


def test_skew_split_serialized_shuffle_parity():
    left_t, right_t = _sides(600, seed=3, skew=0.8)
    conf = dict(SKEW_CONF)
    conf["spark.rapids.shuffle.mode"] = "SERIALIZED"
    on = TpuSession(conf)
    t_on = _join(on, left_t, right_t, parts=(3, 3)).collect()
    off_conf = dict(conf)
    off_conf["spark.rapids.sql.adaptive.enabled"] = "false"
    off = TpuSession(off_conf)
    assert_tables_equal(t_on, _join(off, left_t, right_t,
                                    parts=(3, 3)).collect())


# ---------------------------------------------------------------------------
# broadcast-build reuse across queries
# ---------------------------------------------------------------------------

def test_build_reuse_across_queries_and_invalidation():
    left_t, right_t = _sides()
    s = TpuSession()
    right_cached = s.create_dataframe(right_t, num_partitions=2).cache()

    def q():
        return s.create_dataframe(left_t, num_partitions=3).join(
            right_cached, on="k", how="inner")

    t1 = q().collect()
    first = _decisions(s, "build_reuse")
    t2 = q().collect()
    second = _decisions(s, "build_reuse")
    assert not first and second, \
        f"expected reuse on the 2nd query only: {first!r} / {second!r}"
    assert second[0]["source"] in ("anchor", "digest")
    assert second[0]["dispatches_saved"] >= 1
    assert_tables_equal(t1, t2, ignore_order=True)
    # re-registering ANY temp view advances the table epoch: the digest
    # cache must come back empty
    from spark_rapids_tpu.exec import adaptive as AQ
    epoch = AQ.table_epoch()
    s.create_or_replace_temp_view("r", s.create_dataframe(right_t))
    assert AQ.table_epoch() == epoch + 1


def test_digest_cache_hit_requires_live_anchor():
    """Unit contract of the digest-keyed cache: a hit is honored only
    while the anchor AND its materialization are identity-identical;
    bump_table_version kills every entry."""
    from spark_rapids_tpu import config as C  # noqa: F401
    from spark_rapids_tpu.exec import adaptive as AQ
    from spark_rapids_tpu.plan import nodes as P
    s = TpuSession()
    conf = s.conf
    anchor = P.CachedRelation(P.InMemorySource(
        pa.table({"k": pa.array([1, 2], pa.int64())}), 1))
    anchor.materialized = ["mat"]
    entry = {"build": "b", "keys": "k", "mat": anchor.materialized,
             "build_batches": 3}
    AQ.build_cache_put(conf, anchor, ("skey",), anchor, entry)
    got = AQ.build_cache_get(conf, anchor, ("skey",), anchor)
    assert got is not None and got["build"] == "b"
    # stale materialization -> miss AND eviction
    anchor.materialized = ["remat"]
    assert AQ.build_cache_get(conf, anchor, ("skey",), anchor) is None
    # refill, then a table re-registration invalidates wholesale
    anchor.materialized = ["mat2"]
    entry2 = dict(entry, mat=anchor.materialized)
    AQ.build_cache_put(conf, anchor, ("skey",), anchor, entry2)
    AQ.bump_table_version()
    assert AQ.build_cache_get(conf, anchor, ("skey",), anchor) is None


def test_build_reuse_disabled_by_conf():
    left_t, right_t = _sides()
    s = TpuSession({"spark.rapids.sql.adaptive.buildReuse.enabled":
                    "false"})
    right_cached = s.create_dataframe(right_t, num_partitions=2).cache()

    def q():
        return s.create_dataframe(left_t, num_partitions=3).join(
            right_cached, on="k", how="inner")

    q().collect()
    q().collect()
    # the anchor store (same-session reuse, pre-AQE behavior) may still
    # hit; the point is results stay right and nothing crashes with the
    # digest cache off
    from spark_rapids_tpu.exec import adaptive as AQ
    assert not AQ._BUILD_CACHE


# ---------------------------------------------------------------------------
# measured cost pass
# ---------------------------------------------------------------------------

def _grouped(s, t):
    from spark_rapids_tpu.sql import functions as F
    return s.create_dataframe(t, num_partitions=4).group_by("k").agg(
        F.sum("v").alias("sv"))


def test_measured_cost_collapses_dispatch_bound_exchange(tmp_path):
    rng = np.random.default_rng(8)
    t = pa.table({"k": pa.array(rng.integers(0, 9, 200).astype(np.int64)),
                  "v": pa.array(rng.uniform(0, 10, 200))})
    s = TpuSession({"spark.rapids.obs.historyDir": str(tmp_path)})
    cold = _grouped(s, t).collect()
    assert not _decisions(s, "measured_cost")
    root = s._last_exec
    assert _find_execs(root, "ShuffleExchangeExec"), \
        "precondition: the cold plan must carry a hash exchange"
    # plant an audited verdict for this digest: the shuffle group was
    # pure dispatch overhead (what tools/roofline_report.py shows when
    # the partition count only buys launch tax)
    from spark_rapids_tpu.runtime import obs as OBS
    from spark_rapids_tpu.runtime.obs.history import plan_digest
    digest = plan_digest(_grouped(s, t).plan)
    st = OBS.state()
    assert st is not None and st.history is not None
    rec = next(r for r in st.history.by_digest(digest)
               if r.get("status") == "ok")
    rec2 = dict(rec)
    rec2["roofline"] = {"groups": {"shuffle": {"bound":
                                               "dispatch_overhead"}}}
    st.history.append(rec2)
    warm = _grouped(s, t).collect()
    (d,) = _decisions(s, "measured_cost")
    assert d["digest"] == digest
    assert d["exchange_parts"] == 1
    assert d["coalesce_tiny_rows"] > 0
    root = s._last_exec
    assert not _find_execs(root, "ShuffleExchangeExec"), \
        "hash exchange survived a collapse verdict"
    assert _find_execs(root, "CollectExchangeExec")
    assert_tables_equal(warm, cold, ignore_order=True)
    # the decision landed in the history record too
    last = st.history.by_digest(digest)[-1]
    assert last["aqe"]["counts"] == {"measured_cost": 1}


def test_measured_cost_off_without_history():
    # obs state is process-global, so use a plan digest no other test
    # seeds history for: an un-audited digest must never produce hints
    rng = np.random.default_rng(8)
    t = pa.table({"kk": pa.array(rng.integers(0, 9, 200).astype(np.int64)),
                  "vv": pa.array(rng.uniform(0, 10, 200))})
    from spark_rapids_tpu.sql import functions as F
    s = TpuSession()
    s.create_dataframe(t, num_partitions=3).group_by("kk").agg(
        F.sum("vv").alias("sv")).collect()
    assert not _decisions(s, "measured_cost")


def test_measured_hints_ignore_non_dispatch_verdicts(tmp_path):
    from spark_rapids_tpu.plan import cost as COST
    s = TpuSession({"spark.rapids.obs.historyDir": str(tmp_path)})
    rng = np.random.default_rng(8)
    t = pa.table({"k": pa.array(rng.integers(0, 9, 200).astype(np.int64)),
                  "v": pa.array(rng.uniform(0, 10, 200))})
    df = _grouped(s, t)
    df.collect()
    from spark_rapids_tpu.runtime import obs as OBS
    from spark_rapids_tpu.runtime.obs.history import plan_digest
    digest = plan_digest(df.plan)
    st = OBS.state()
    rec = dict(st.history.by_digest(digest)[-1])
    rec["roofline"] = {"groups": {"shuffle": {"bound": "memory"},
                                  "device_compute": {"bound": "compute"}}}
    st.history.append(rec)
    COST.reset_for_tests()
    assert COST.measured_hints(df.plan, s.conf) is None


def test_explain_analyze_has_adaptive_section():
    left_t, right_t = _sides()
    s = TpuSession(AQE_ON)
    _join(s, left_t, right_t).collect()
    text = s.explain_analyze()
    assert "-- adaptive (" in text
    assert "broadcast_conversion" in text


def test_aqe_counters_exported():
    from spark_rapids_tpu.runtime import obs as OBS
    left_t, right_t = _sides()
    s = TpuSession(AQE_ON)
    _join(s, left_t, right_t).collect()
    st = OBS.state()
    if st is None:
        pytest.skip("obs not configured in this environment")
    snap = st.registry.snapshot()
    assert any(k.startswith("rapids_aqe_decisions_total") for k in snap)
    assert any(k.startswith("rapids_aqe_dispatches_saved_total")
               for k in snap)
