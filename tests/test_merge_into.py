"""MERGE INTO differential tests (reference GpuMergeIntoCommand.scala
semantics: upsert, delete, conditional clauses, cardinality check)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql.merge import merge_into
from spark_rapids_tpu.expr.core import col, lit, SparkException

from asserts import assert_tables_equal


@pytest.fixture
def session():
    return TpuSession()


def _target(s):
    return s.create_dataframe({
        "id": pa.array([1, 2, 3, 4, 5], pa.int64()),
        "v": pa.array([10.0, 20.0, 30.0, 40.0, 50.0]),
        "tag": pa.array(["a", "b", "c", "d", "e"]),
    })


def _source(s):
    return s.create_dataframe({
        "id": pa.array([2, 4, 6, 7], pa.int64()),
        "v": pa.array([200.0, 400.0, 600.0, 700.0]),
        "tag": pa.array(["B", "D", "F", "G"]),
    })


def _diff(m):
    tpu = m.result().collect()
    cpu = m.result().collect_cpu()
    assert_tables_equal(tpu, cpu, ignore_order=True)
    return tpu.to_pylist()


def test_merge_upsert(session):
    rows = _diff(
        merge_into(_target(session), _source(session), on=["id"])
        .when_matched_update({"v": col("__src_v"), "tag": col("__src_tag")})
        .when_not_matched_insert())
    got = {r["id"]: (r["v"], r["tag"]) for r in rows}
    assert got[2] == (200.0, "B") and got[4] == (400.0, "D")
    assert got[1] == (10.0, "a")                      # untouched
    assert got[6] == (600.0, "F") and got[7] == (700.0, "G")  # inserted
    assert len(got) == 7


def test_merge_update_only(session):
    rows = _diff(
        merge_into(_target(session), _source(session), on=["id"])
        .when_matched_update({"v": col("__src_v") * lit(2.0)}))
    got = {r["id"]: r["v"] for r in rows}
    assert got[2] == 400.0 and got[4] == 800.0 and len(got) == 5


def test_merge_delete(session):
    rows = _diff(
        merge_into(_target(session), _source(session), on=["id"])
        .when_matched_delete())
    assert sorted(r["id"] for r in rows) == [1, 3, 5]


def test_merge_conditional_clauses(session):
    rows = _diff(
        merge_into(_target(session), _source(session), on=["id"])
        .when_matched_update({"v": col("__src_v")},
                             condition=col("__src_v") > lit(300.0))
        .when_not_matched_insert(condition=col("v") < lit(650.0)))
    got = {r["id"]: r["v"] for r in rows}
    assert got[2] == 20.0      # condition false -> untouched
    assert got[4] == 400.0     # updated
    assert 6 in got and 7 not in got  # insert condition
    assert len(got) == 6


def test_merge_insert_defaults_missing_to_null(session):
    src = session.create_dataframe({
        "id": pa.array([9], pa.int64()), "v": pa.array([900.0])})
    rows = _diff(
        merge_into(_target(session), src, on=["id"])
        .when_not_matched_insert())
    got = {r["id"]: r["tag"] for r in rows}
    assert got[9] is None and len(got) == 6


def test_merge_cardinality_violation(session):
    dup = session.create_dataframe({
        "id": pa.array([2, 2], pa.int64()),
        "v": pa.array([1.0, 2.0]),
        "tag": pa.array(["x", "y"])})
    with pytest.raises(SparkException, match="multiple source rows"):
        merge_into(_target(session), dup, on=["id"]) \
            .when_matched_update({"v": col("__src_v")}).result()
    # but duplicates that match NO target row are fine
    dup2 = session.create_dataframe({
        "id": pa.array([100, 100], pa.int64()),
        "v": pa.array([1.0, 2.0]),
        "tag": pa.array(["x", "y"])})
    rows = _diff(merge_into(_target(session), dup2, on=["id"])
                 .when_matched_update({"v": col("__src_v")}))
    assert len(rows) == 5


def test_merge_execute_writeback(session, tmp_path):
    out = str(tmp_path / "merged")
    merge_into(_target(session), _source(session), on=["id"]) \
        .when_matched_update({"v": col("__src_v")}) \
        .when_not_matched_insert() \
        .execute_to(out)
    back = session.read_parquet(out).to_pydict()
    got = dict(zip(back["id"], back["v"]))
    assert got[2] == 200.0 and got[6] == 600.0 and len(got) == 7
