"""SQL string frontend tests: each query differentially checked against
the equivalent DataFrame-algebra build (sql/parser.py; the reference
receives SQL via Catalyst, a standalone engine parses its own)."""
import numpy as np
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import SparkException, col, lit


@pytest.fixture
def session():
    s = TpuSession()
    rng = np.random.default_rng(11)
    s.create_or_replace_temp_view("t", s.create_dataframe(
        {"k": rng.integers(0, 5, 300).tolist(),
         "v": np.round(rng.uniform(0, 10, 300), 3).tolist(),
         "name": [f"n{i % 17}" for i in range(300)]}))
    s.create_or_replace_temp_view("d", s.create_dataframe(
        {"k": [0, 1, 2, 3, 4], "label": ["a", "b", "c", "d", "e"]}))
    return s


def test_select_where_group_having_order_limit(session):
    got = session.sql(
        "SELECT k, SUM(v) AS sv, COUNT(*) AS n FROM t WHERE v > 2.0 "
        "GROUP BY k HAVING COUNT(*) > 10 ORDER BY sv DESC LIMIT 3"
    ).to_pydict()
    t = session.table("t")
    want = (t.filter(col("v") > lit(2.0)).group_by("k")
            .agg(F.sum(col("v")).alias("sv"), F.count().alias("n"))
            .filter(col("n") > lit(10))
            .select(col("k"), col("sv"), col("n"))
            .order_by(col("sv").desc()).limit(3).to_pydict())
    assert got == want


def test_join_and_expressions(session):
    got = session.sql(
        "SELECT t.k, label, v * 2 + 1 AS x FROM t JOIN d ON t.k = d.k "
        "WHERE name LIKE 'n1%' AND v BETWEEN 1.0 AND 9.0 "
        "ORDER BY x ASC, label ASC LIMIT 20").to_pydict()
    t, d = session.table("t"), session.table("d")
    from spark_rapids_tpu.expr.strings import Like
    want = (t.join(d, on=[(col("k"), col("k"))])
            .filter(Like(col("name"), "n1%")
                    & (col("v") >= lit(1.0)) & (col("v") <= lit(9.0)))
            .select(col("k"), col("label"),
                    (col("v") * lit(2) + lit(1)).alias("x"))
            .order_by(col("x").asc(), col("label").asc())
            .limit(20).to_pydict())
    assert got == want


def test_case_cast_distinct_union(session):
    got = session.sql(
        "SELECT DISTINCT CASE WHEN v >= 5.0 THEN 'hi' ELSE 'lo' END AS b "
        "FROM t ORDER BY b ASC").to_pydict()
    assert got["b"] == ["hi", "lo"]
    got = session.sql("SELECT CAST(v AS bigint) AS iv FROM t "
                      "ORDER BY iv DESC LIMIT 1").to_pydict()
    assert isinstance(got["iv"][0], int)
    u = session.sql("SELECT k FROM d WHERE k < 1 "
                    "UNION ALL SELECT k FROM d WHERE k > 3").to_pydict()
    assert sorted(u["k"]) == [0, 4]


def test_scalar_functions_and_in(session):
    got = session.sql(
        "SELECT upper(name) AS u, substring(name, 1, 2) AS p FROM t "
        "WHERE k IN (1, 3) LIMIT 5").to_pydict()
    assert all(s == s.upper() for s in got["u"])
    assert all(len(s) <= 2 for s in got["p"])


def test_global_agg_and_star(session):
    got = session.sql("SELECT avg(v) AS m, min(k) AS lo FROM t"
                      ).to_pydict()
    t = session.table("t")
    want = t.agg(F.avg(col("v")).alias("m"),
                 F.min(col("k")).alias("lo")).to_pydict()
    assert got == want
    assert session.sql("SELECT * FROM d ORDER BY k ASC").to_pydict()[
        "label"] == ["a", "b", "c", "d", "e"]


def test_semi_anti_joins(session):
    semi = session.sql("SELECT k FROM d LEFT SEMI JOIN t ON d.k = t.k "
                       "ORDER BY k ASC").to_pydict()
    anti = session.sql("SELECT k FROM d LEFT ANTI JOIN t ON d.k = t.k "
                       ).to_pydict()
    present = set(session.table("t").to_pydict()["k"])
    assert set(semi["k"]) == present & {0, 1, 2, 3, 4}
    assert set(anti["k"]) == {0, 1, 2, 3, 4} - present


def test_null_handling_and_not(session):
    s2 = TpuSession()
    import pyarrow as pa
    s2.create_or_replace_temp_view("n", s2.create_dataframe(
        pa.table({"x": pa.array([1.0, None, 3.0], pa.float64())})))
    assert s2.sql("SELECT x FROM n WHERE x IS NULL").to_pydict()["x"] \
        == [None]
    assert sorted(s2.sql(
        "SELECT x FROM n WHERE x IS NOT NULL").to_pydict()["x"]) \
        == [1.0, 3.0]
    assert s2.sql("SELECT x FROM n WHERE NOT x = 1.0").to_pydict()["x"] \
        == [3.0]
    assert s2.sql("SELECT x FROM n WHERE x NOT IN (1.0)").to_pydict()[
        "x"] == [3.0]


def test_parse_errors_are_loud(session):
    for bad in ("SELECT FROM t",
                "SELECT k FROM t WHERE",
                "SELECT k FROM nosuch",
                "SELECT k, SUM(v) FROM t",       # agg without GROUP BY
                "SELECT nosuchfn(k) FROM t",
                "SELECT k FROM t ORDER BY k ASC extra"):
        with pytest.raises((SparkException, KeyError)):
            session.sql(bad).collect()


def test_order_by_nulls_placement(session):
    import pyarrow as pa
    s2 = TpuSession()
    s2.create_or_replace_temp_view("n", s2.create_dataframe(
        pa.table({"x": pa.array([2.0, None, 1.0], pa.float64())})))
    asc = s2.sql("SELECT x FROM n ORDER BY x ASC NULLS LAST"
                 ).to_pydict()["x"]
    assert asc == [1.0, 2.0, None]
    desc = s2.sql("SELECT x FROM n ORDER BY x DESC NULLS FIRST"
                  ).to_pydict()["x"]
    assert desc == [None, 2.0, 1.0]


def test_union_scoping_and_dedup(session):
    # ORDER BY / LIMIT bind to the WHOLE union, not the last branch
    got = session.sql(
        "SELECT k FROM d WHERE k < 1 UNION ALL "
        "SELECT k FROM d WHERE k > 3 ORDER BY k DESC LIMIT 1"
    ).to_pydict()
    assert got["k"] == [4]
    # bare UNION deduplicates
    u = session.sql("SELECT k FROM d UNION SELECT k FROM d").to_pydict()
    assert sorted(u["k"]) == [0, 1, 2, 3, 4]


def test_having_without_group_by(session):
    # global aggregate: HAVING filters the single row
    got = session.sql("SELECT count(*) AS n FROM t "
                      "HAVING count(*) > 1000000").to_pydict()
    assert got["n"] == []
    with pytest.raises(SparkException):
        session.sql("SELECT k FROM t HAVING k > 1").collect()


def test_scientific_notation_and_negative_args(session):
    got = session.sql("SELECT v * 1e3 AS x FROM t ORDER BY x ASC LIMIT 1"
                      ).to_pydict()
    t = session.table("t")
    want = (t.select((col("v") * lit(1000.0)).alias("x"))
            .order_by(col("x").asc()).limit(1).to_pydict())
    assert got == want
    got = session.sql("SELECT substring(name, -2, 2) AS tail FROM t "
                      "LIMIT 3").to_pydict()
    names = session.table("t").limit(3).to_pydict()["name"]
    assert got["tail"] == [n[-2:] for n in names]


def test_cte_and_derived_table(session):
    got = session.sql(
        "WITH agg AS (SELECT k, SUM(v) AS sv FROM t GROUP BY k), "
        "top AS (SELECT k FROM agg ORDER BY sv DESC LIMIT 2) "
        "SELECT count(*) AS n FROM t JOIN top ON t.k = top.k"
    ).to_pydict()
    t = session.table("t")
    top = (t.group_by("k").agg(F.sum(col("v")).alias("sv"))
           .order_by(col("sv").desc()).limit(2).select(col("k")))
    want = t.join(top, on=[(col("k"), col("k"))]).count()
    assert got["n"] == [want]
    sub = session.sql(
        "SELECT k FROM (SELECT k, MAX(v) AS mx FROM t GROUP BY k) s "
        "WHERE mx > 9.0 ORDER BY k ASC").to_pydict()
    want2 = (t.group_by("k").agg(F.max(col("v")).alias("mx"))
             .filter(col("mx") > lit(9.0)).select(col("k"))
             .order_by(col("k").asc()).to_pydict())
    assert sub == want2
    # a CTE name must not leak across queries
    with pytest.raises(SparkException):
        session.sql("SELECT k FROM agg").collect()


def test_order_by_alias_plus_hidden_column(session):
    # valid SQL: one sort key is an output alias, the other is a
    # non-projected source column
    got = session.sql("SELECT v AS val FROM t ORDER BY val ASC, k ASC "
                      "LIMIT 5").to_pydict()
    t = session.table("t")
    want = (t.order_by(col("v").asc(), col("k").asc())
            .select(col("v").alias("val")).limit(5).to_pydict())
    assert got == want
    # DISTINCT exposes output columns only — loud SparkException,
    # not a raw KeyError
    with pytest.raises(SparkException):
        session.sql("SELECT DISTINCT k FROM t ORDER BY v").collect()


# -- round-5 surface: subqueries, set ops, grouping sets ---------------------


def _rows(df):
    return sorted(df.collect().to_pylist(), key=str)


def test_exists_and_not_exists_subquery(session):
    got = _rows(session.sql(
        "SELECT k, label FROM d WHERE EXISTS "
        "(SELECT * FROM t WHERE t.k = d.k AND v > 9.0)"))
    t, d = session.table("t"), session.table("d")
    keep = t.filter(col("v") > lit(9.0))
    want = _rows(d.join(keep, on=[(col("k"), col("k"))], how="left_semi"))
    assert got == want
    got_n = _rows(session.sql(
        "SELECT k FROM d WHERE NOT EXISTS "
        "(SELECT * FROM t WHERE t.k = d.k AND v > 9.0)"))
    want_n = _rows(d.join(keep, on=[(col("k"), col("k"))],
                          how="left_anti").select(col("k")))
    assert got_n == want_n
    assert len(got) + len(got_n) == 5


def test_in_subquery_and_not_in(session):
    got = _rows(session.sql(
        "SELECT label FROM d WHERE k IN "
        "(SELECT k FROM t WHERE v > 9.5)"))
    hot = {r["k"] for r in session.table("t").filter(
        col("v") > lit(9.5)).collect().to_pylist()}
    want = sorted([{"label": l} for k, l in
                   zip([0, 1, 2, 3, 4], ["a", "b", "c", "d", "e"])
                   if k in hot], key=str)
    assert got == want
    got_n = _rows(session.sql(
        "SELECT label FROM d WHERE k NOT IN "
        "(SELECT k FROM t WHERE v > 9.5)"))
    want_n = sorted([{"label": l} for k, l in
                     zip([0, 1, 2, 3, 4], ["a", "b", "c", "d", "e"])
                     if k not in hot], key=str)
    assert got_n == want_n


def test_not_in_subquery_null_aware(session):
    # any NULL in the subquery result empties a NOT IN (three-valued
    # logic); Spark handles this as a null-aware anti join
    s = session
    s.create_or_replace_temp_view("withnull", s.create_dataframe(
        {"x": [1, None, 2]}))
    got = s.sql("SELECT k FROM d WHERE k NOT IN "
                "(SELECT x FROM withnull)").collect()
    assert got.num_rows == 0
    got2 = _rows(s.sql("SELECT k FROM d WHERE k IN "
                       "(SELECT x FROM withnull)"))
    assert got2 == [{"k": 1}, {"k": 2}]


def test_scalar_subquery(session):
    got = _rows(session.sql(
        "SELECT k FROM d WHERE k > (SELECT AVG(k) FROM t)"))
    avg = np.mean([r["k"] for r in
                   session.table("t").collect().to_pylist()])
    want = sorted([{"k": k} for k in [0, 1, 2, 3, 4] if k > avg],
                  key=str)
    assert got == want


def test_grouped_in_subquery_with_having(session):
    got = _rows(session.sql(
        "SELECT label FROM d WHERE k IN "
        "(SELECT k FROM t GROUP BY k HAVING COUNT(*) >= 55)"))
    counts = {}
    for r in session.table("t").collect().to_pylist():
        counts[r["k"]] = counts.get(r["k"], 0) + 1
    keep = {k for k, n in counts.items() if n >= 55}
    want = sorted([{"label": l} for k, l in
                   zip([0, 1, 2, 3, 4], ["a", "b", "c", "d", "e"])
                   if k in keep], key=str)
    assert got == want and 0 < len(got) < 5


def test_intersect_and_except(session):
    s = session
    s.create_or_replace_temp_view("left5", s.create_dataframe(
        {"x": [1, 2, 2, 3, 4]}))
    s.create_or_replace_temp_view("right3", s.create_dataframe(
        {"x": [2, 3, 3, 5]}))
    assert _rows(s.sql("SELECT x FROM left5 INTERSECT "
                       "SELECT x FROM right3")) == [{"x": 2}, {"x": 3}]
    assert _rows(s.sql("SELECT x FROM left5 EXCEPT "
                       "SELECT x FROM right3")) == [{"x": 1}, {"x": 4}]
    assert _rows(s.sql("SELECT x FROM left5 MINUS "
                       "SELECT x FROM right3")) == [{"x": 1}, {"x": 4}]


def test_rollup_sql_matches_manual_union(session):
    got = _rows(session.sql(
        "SELECT k, name, SUM(v) AS sv, COUNT(*) AS n, "
        "GROUPING(name) AS gn, GROUPING_ID() AS gid "
        "FROM t GROUP BY ROLLUP(k, name)"))
    t = session.table("t")
    rows = t.collect().to_pylist()
    import collections
    fine = collections.defaultdict(lambda: [0.0, 0])
    sub = collections.defaultdict(lambda: [0.0, 0])
    tot = [0.0, 0]
    for r in rows:
        for acc in (fine[(r["k"], r["name"])], sub[r["k"]], tot):
            acc[0] += r["v"]
            acc[1] += 1
    want = []
    for (k, nm), (sv, n) in fine.items():
        want.append({"k": k, "name": nm, "sv": sv, "n": n,
                     "gn": 0, "gid": 0})
    for k, (sv, n) in sub.items():
        want.append({"k": k, "name": None, "sv": sv, "n": n,
                     "gn": 1, "gid": 1})
    want.append({"k": None, "name": None, "sv": tot[0], "n": tot[1],
                 "gn": 1, "gid": 3})
    for w in want:
        w["sv"] = round(w["sv"], 6)
    for g in got:
        g["sv"] = round(g["sv"], 6)
    assert got == sorted(want, key=str)


def test_cube_and_grouping_sets_row_counts(session):
    t_rows = session.table("t").collect().to_pylist()
    ks = {r["k"] for r in t_rows}
    names = {r["name"] for r in t_rows}
    pairs = {(r["k"], r["name"]) for r in t_rows}
    cube = session.sql(
        "SELECT k, name, COUNT(*) AS n FROM t GROUP BY CUBE(k, name)"
    ).collect()
    assert cube.num_rows == len(pairs) + len(ks) + len(names) + 1
    gs = session.sql(
        "SELECT k, name, COUNT(*) AS n FROM t "
        "GROUP BY GROUPING SETS((k), (name))").collect()
    assert gs.num_rows == len(ks) + len(names)


def test_rollup_dataframe_api_differential(session):
    from asserts import assert_tpu_and_cpu_are_equal_collect
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.table("t").rollup("k", "name")
        .agg(F.sum(col("v")).alias("sv"), F.count("*").alias("n"),
             F.grouping(col("k")).alias("gk"),
             F.grouping_id().alias("gid")),
        session, approx_float=1e-9, ignore_order=True)


def test_correlated_exists_same_column_name(session):
    # t.k = d.k must stay a CORRELATION even though both columns are
    # named k (qualified refs must not collapse to k = k)
    s = TpuSession()
    s.create_or_replace_temp_view("tt", s.create_dataframe(
        {"k": [0, 0, 1], "v": [9.5, 1.0, 1.0]}))
    s.create_or_replace_temp_view("dd", s.create_dataframe(
        {"k": [0, 1]}))
    got = _rows(s.sql("SELECT k FROM dd WHERE EXISTS "
                      "(SELECT * FROM tt WHERE tt.k = dd.k AND v > 9.0)"))
    assert got == [{"k": 0}]
    got_n = _rows(s.sql(
        "SELECT k FROM dd WHERE NOT EXISTS "
        "(SELECT * FROM tt WHERE tt.k = dd.k AND v > 9.0)"))
    assert got_n == [{"k": 1}]


def test_not_in_empty_subquery_keeps_null_probe(session):
    # NULL NOT IN (empty set) is TRUE: no comparisons happen
    s = TpuSession()
    s.create_or_replace_temp_view("dn", s.create_dataframe(
        {"k": [1, None]}))
    s.create_or_replace_temp_view("src", s.create_dataframe(
        {"x": [200, 300]}))
    got = _rows(s.sql("SELECT k FROM dn WHERE k NOT IN "
                      "(SELECT x FROM src WHERE x > 500)"))
    assert got == sorted([{"k": 1}, {"k": None}], key=str)


def test_subquery_outside_where_is_rejected(session):
    with pytest.raises(SparkException):
        session.sql("SELECT EXISTS(SELECT * FROM t) AS e FROM t")
    with pytest.raises(SparkException):
        session.sql("SELECT k, COUNT(*) FROM t GROUP BY k "
                    "HAVING EXISTS(SELECT * FROM t)")
