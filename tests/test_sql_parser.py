"""SQL string frontend tests: each query differentially checked against
the equivalent DataFrame-algebra build (sql/parser.py; the reference
receives SQL via Catalyst, a standalone engine parses its own)."""
import numpy as np
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import SparkException, col, lit


@pytest.fixture
def session():
    s = TpuSession()
    rng = np.random.default_rng(11)
    s.create_or_replace_temp_view("t", s.create_dataframe(
        {"k": rng.integers(0, 5, 300).tolist(),
         "v": np.round(rng.uniform(0, 10, 300), 3).tolist(),
         "name": [f"n{i % 17}" for i in range(300)]}))
    s.create_or_replace_temp_view("d", s.create_dataframe(
        {"k": [0, 1, 2, 3, 4], "label": ["a", "b", "c", "d", "e"]}))
    return s


def test_select_where_group_having_order_limit(session):
    got = session.sql(
        "SELECT k, SUM(v) AS sv, COUNT(*) AS n FROM t WHERE v > 2.0 "
        "GROUP BY k HAVING COUNT(*) > 10 ORDER BY sv DESC LIMIT 3"
    ).to_pydict()
    t = session.table("t")
    want = (t.filter(col("v") > lit(2.0)).group_by("k")
            .agg(F.sum(col("v")).alias("sv"), F.count().alias("n"))
            .filter(col("n") > lit(10))
            .select(col("k"), col("sv"), col("n"))
            .order_by(col("sv").desc()).limit(3).to_pydict())
    assert got == want


def test_join_and_expressions(session):
    got = session.sql(
        "SELECT t.k, label, v * 2 + 1 AS x FROM t JOIN d ON t.k = d.k "
        "WHERE name LIKE 'n1%' AND v BETWEEN 1.0 AND 9.0 "
        "ORDER BY x ASC, label ASC LIMIT 20").to_pydict()
    t, d = session.table("t"), session.table("d")
    from spark_rapids_tpu.expr.strings import Like
    want = (t.join(d, on=[(col("k"), col("k"))])
            .filter(Like(col("name"), "n1%")
                    & (col("v") >= lit(1.0)) & (col("v") <= lit(9.0)))
            .select(col("k"), col("label"),
                    (col("v") * lit(2) + lit(1)).alias("x"))
            .order_by(col("x").asc(), col("label").asc())
            .limit(20).to_pydict())
    assert got == want


def test_case_cast_distinct_union(session):
    got = session.sql(
        "SELECT DISTINCT CASE WHEN v >= 5.0 THEN 'hi' ELSE 'lo' END AS b "
        "FROM t ORDER BY b ASC").to_pydict()
    assert got["b"] == ["hi", "lo"]
    got = session.sql("SELECT CAST(v AS bigint) AS iv FROM t "
                      "ORDER BY iv DESC LIMIT 1").to_pydict()
    assert isinstance(got["iv"][0], int)
    u = session.sql("SELECT k FROM d WHERE k < 1 "
                    "UNION ALL SELECT k FROM d WHERE k > 3").to_pydict()
    assert sorted(u["k"]) == [0, 4]


def test_scalar_functions_and_in(session):
    got = session.sql(
        "SELECT upper(name) AS u, substring(name, 1, 2) AS p FROM t "
        "WHERE k IN (1, 3) LIMIT 5").to_pydict()
    assert all(s == s.upper() for s in got["u"])
    assert all(len(s) <= 2 for s in got["p"])


def test_global_agg_and_star(session):
    got = session.sql("SELECT avg(v) AS m, min(k) AS lo FROM t"
                      ).to_pydict()
    t = session.table("t")
    want = t.agg(F.avg(col("v")).alias("m"),
                 F.min(col("k")).alias("lo")).to_pydict()
    assert got == want
    assert session.sql("SELECT * FROM d ORDER BY k ASC").to_pydict()[
        "label"] == ["a", "b", "c", "d", "e"]


def test_semi_anti_joins(session):
    semi = session.sql("SELECT k FROM d LEFT SEMI JOIN t ON d.k = t.k "
                       "ORDER BY k ASC").to_pydict()
    anti = session.sql("SELECT k FROM d LEFT ANTI JOIN t ON d.k = t.k "
                       ).to_pydict()
    present = set(session.table("t").to_pydict()["k"])
    assert set(semi["k"]) == present & {0, 1, 2, 3, 4}
    assert set(anti["k"]) == {0, 1, 2, 3, 4} - present


def test_null_handling_and_not(session):
    s2 = TpuSession()
    import pyarrow as pa
    s2.create_or_replace_temp_view("n", s2.create_dataframe(
        pa.table({"x": pa.array([1.0, None, 3.0], pa.float64())})))
    assert s2.sql("SELECT x FROM n WHERE x IS NULL").to_pydict()["x"] \
        == [None]
    assert sorted(s2.sql(
        "SELECT x FROM n WHERE x IS NOT NULL").to_pydict()["x"]) \
        == [1.0, 3.0]
    assert s2.sql("SELECT x FROM n WHERE NOT x = 1.0").to_pydict()["x"] \
        == [3.0]
    assert s2.sql("SELECT x FROM n WHERE x NOT IN (1.0)").to_pydict()[
        "x"] == [3.0]


def test_parse_errors_are_loud(session):
    for bad in ("SELECT FROM t",
                "SELECT k FROM t WHERE",
                "SELECT k FROM nosuch",
                "SELECT k, SUM(v) FROM t",       # agg without GROUP BY
                "SELECT nosuchfn(k) FROM t",
                "SELECT k FROM t ORDER BY k ASC extra"):
        with pytest.raises((SparkException, KeyError)):
            session.sql(bad).collect()


def test_order_by_nulls_placement(session):
    import pyarrow as pa
    s2 = TpuSession()
    s2.create_or_replace_temp_view("n", s2.create_dataframe(
        pa.table({"x": pa.array([2.0, None, 1.0], pa.float64())})))
    asc = s2.sql("SELECT x FROM n ORDER BY x ASC NULLS LAST"
                 ).to_pydict()["x"]
    assert asc == [1.0, 2.0, None]
    desc = s2.sql("SELECT x FROM n ORDER BY x DESC NULLS FIRST"
                  ).to_pydict()["x"]
    assert desc == [None, 2.0, 1.0]


def test_union_scoping_and_dedup(session):
    # ORDER BY / LIMIT bind to the WHOLE union, not the last branch
    got = session.sql(
        "SELECT k FROM d WHERE k < 1 UNION ALL "
        "SELECT k FROM d WHERE k > 3 ORDER BY k DESC LIMIT 1"
    ).to_pydict()
    assert got["k"] == [4]
    # bare UNION deduplicates
    u = session.sql("SELECT k FROM d UNION SELECT k FROM d").to_pydict()
    assert sorted(u["k"]) == [0, 1, 2, 3, 4]


def test_having_without_group_by(session):
    # global aggregate: HAVING filters the single row
    got = session.sql("SELECT count(*) AS n FROM t "
                      "HAVING count(*) > 1000000").to_pydict()
    assert got["n"] == []
    with pytest.raises(SparkException):
        session.sql("SELECT k FROM t HAVING k > 1").collect()


def test_scientific_notation_and_negative_args(session):
    got = session.sql("SELECT v * 1e3 AS x FROM t ORDER BY x ASC LIMIT 1"
                      ).to_pydict()
    t = session.table("t")
    want = (t.select((col("v") * lit(1000.0)).alias("x"))
            .order_by(col("x").asc()).limit(1).to_pydict())
    assert got == want
    got = session.sql("SELECT substring(name, -2, 2) AS tail FROM t "
                      "LIMIT 3").to_pydict()
    names = session.table("t").limit(3).to_pydict()["name"]
    assert got["tail"] == [n[-2:] for n in names]


def test_cte_and_derived_table(session):
    got = session.sql(
        "WITH agg AS (SELECT k, SUM(v) AS sv FROM t GROUP BY k), "
        "top AS (SELECT k FROM agg ORDER BY sv DESC LIMIT 2) "
        "SELECT count(*) AS n FROM t JOIN top ON t.k = top.k"
    ).to_pydict()
    t = session.table("t")
    top = (t.group_by("k").agg(F.sum(col("v")).alias("sv"))
           .order_by(col("sv").desc()).limit(2).select(col("k")))
    want = t.join(top, on=[(col("k"), col("k"))]).count()
    assert got["n"] == [want]
    sub = session.sql(
        "SELECT k FROM (SELECT k, MAX(v) AS mx FROM t GROUP BY k) s "
        "WHERE mx > 9.0 ORDER BY k ASC").to_pydict()
    want2 = (t.group_by("k").agg(F.max(col("v")).alias("mx"))
             .filter(col("mx") > lit(9.0)).select(col("k"))
             .order_by(col("k").asc()).to_pydict())
    assert sub == want2
    # a CTE name must not leak across queries
    with pytest.raises(SparkException):
        session.sql("SELECT k FROM agg").collect()


def test_order_by_alias_plus_hidden_column(session):
    # valid SQL: one sort key is an output alias, the other is a
    # non-projected source column
    got = session.sql("SELECT v AS val FROM t ORDER BY val ASC, k ASC "
                      "LIMIT 5").to_pydict()
    t = session.table("t")
    want = (t.order_by(col("v").asc(), col("k").asc())
            .select(col("v").alias("val")).limit(5).to_pydict())
    assert got == want
    # DISTINCT exposes output columns only — loud SparkException,
    # not a raw KeyError
    with pytest.raises(SparkException):
        session.sql("SELECT DISTINCT k FROM t ORDER BY v").collect()
