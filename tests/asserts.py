"""Differential assertion helpers.

Reference parity: integration_tests/src/main/python/asserts.py --
assert_gpu_and_cpu_are_equal_collect (:583) runs the same query on CPU and
GPU Spark and diffs; assert_gpu_fallback_collect (:443) asserts a specific
exec fell back. Here the TPU engine is diffed against the independent
pandas/numpy CPU backend.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import pyarrow as pa


def _canon(table: pa.Table):
    return table.to_pylist()


def _sort_key(row):
    out = []
    for k in sorted(row):
        v = row[k]
        if v is None:
            out.append((0, ""))
        elif isinstance(v, float) and math.isnan(v):
            out.append((2, "nan"))
        else:
            out.append((1, str(v)))
    return out


def _row_eq(a, b, approx: Optional[float]) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        if approx is not None:
            if fa == fb:
                return True
            denom = max(abs(fa), abs(fb), 1e-300)
            return abs(fa - fb) / denom < approx or abs(fa - fb) < 1e-12
        return fa == fb
    return a == b


def _canon_arrays(rows, names):
    """Sort list-valued cells (None-first) — for aggregates whose element
    order Spark leaves unspecified (collect_set)."""
    def key(v):
        return (v is not None, v if v is not None else 0)
    for r in rows:
        for k in names:
            if isinstance(r.get(k), list):
                r[k] = sorted(r[k], key=key)
    return rows


def assert_tables_equal(tpu: pa.Table, cpu: pa.Table,
                        ignore_order: bool = False,
                        approx_float: Optional[float] = None,
                        canonicalize_arrays: bool = False) -> None:
    assert tpu.schema.names == cpu.schema.names, \
        f"schema names differ: {tpu.schema.names} vs {cpu.schema.names}"
    trows = _canon(tpu)
    crows = _canon(cpu)
    if canonicalize_arrays:
        _canon_arrays(trows, tpu.schema.names)
        _canon_arrays(crows, tpu.schema.names)
    assert len(trows) == len(crows), \
        f"row count differs: tpu={len(trows)} cpu={len(crows)}\n" \
        f"tpu={trows[:20]}\ncpu={crows[:20]}"
    if ignore_order:
        trows = sorted(trows, key=_sort_key)
        crows = sorted(crows, key=_sort_key)
    for i, (tr, cr) in enumerate(zip(trows, crows)):
        for k in tpu.schema.names:
            assert _row_eq(tr[k], cr[k], approx_float), \
                (f"row {i} col {k}: tpu={tr[k]!r} cpu={cr[k]!r}\n"
                 f"tpu rows: {trows[max(0,i-2):i+3]}\n"
                 f"cpu rows: {crows[max(0,i-2):i+3]}")


def assert_tpu_and_cpu_are_equal_collect(df_fn: Callable, session,
                                         ignore_order: bool = False,
                                         approx_float: Optional[float] = None,
                                         conf: Optional[dict] = None,
                                         canonicalize_arrays: bool = False):
    """df_fn(session) -> DataFrame. Runs it on the TPU engine and the CPU
    backend and diffs results."""
    if conf:
        from spark_rapids_tpu.sql.session import TpuSession
        overrides = dict(session.conf._values)
        overrides.update(conf)
        session = TpuSession(overrides)
    df = df_fn(session)
    tpu = df.collect()
    cpu = df.collect_cpu()
    assert_tables_equal(tpu, cpu, ignore_order, approx_float,
                        canonicalize_arrays=canonicalize_arrays)
    return tpu


def assert_fallback_collect(df_fn: Callable, session, fallback_exec: str,
                            ignore_order: bool = False):
    """Asserts results match AND that the named plan node fell back to CPU
    (reference assert_gpu_fallback_collect)."""
    from spark_rapids_tpu.plan.overrides import wrap_and_tag
    df = df_fn(session)
    meta = wrap_and_tag(df.plan, session.conf)
    found = []

    def walk(m):
        if type(m.plan).__name__ == fallback_exec and not m.can_run_on_tpu:
            found.append(m)
        for c in m.children:
            walk(c)

    walk(meta)
    assert found, f"{fallback_exec} did not fall back:\n{meta.explain(all_ops=True)}"
    tpu = df.collect()
    cpu = df.collect_cpu()
    assert_tables_equal(tpu, cpu, ignore_order)
    return tpu
