"""Device regex NFA tests: differential against Python `re` over a corpus
(reference RegexParser/fuzz strategy, SURVEY.md §2.5 regex transpiler)."""
import re

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col

from asserts import assert_tpu_and_cpu_are_equal_collect, assert_fallback_collect

CORPUS = ["", "a", "abc", "aabbb", "hello world", "123", "a1b2", "  pad  ",
          "ABC", "abcabc", "xyz", "a.b", "[x]", "über", "日本語abc", "\n",
          "line1\nline2", "aaaa", "zzz9", "foo_bar", "a-b", "3.14", "-42"]

SUPPORTED_PATTERNS = [
    "abc", "^abc", "abc$", "^abc$", "a+b*c?", "[abc]+", "[^abc]+",
    "[a-z0-9]+", r"\d+", r"\w+", r"\s", r"\d{2,3}", "a{2}", "(ab)+c",
    "ab|cd|ef", "^(foo|bar)_", "a.c", ".*", "x?yz", r"[-+]?\d+",
    r"\d+\.\d+", "(a|b)(c|d)", "^$",
]

UNSUPPORTED_PATTERNS = [
    r"(?i)abc", r"a(?=b)", r"(a)\1", r"a*?", r"a*+", r"\p{L}", "日本",
]


def _nfa_matches(pattern, corpus):
    import jax.numpy as jnp
    from spark_rapids_tpu.expr import regex as RX
    nfa = RX.compile_pattern(pattern, mode="find")
    data = "".join(corpus).encode("utf-8")
    offs = [0]
    for s in corpus:
        offs.append(offs[-1] + len(s.encode("utf-8")))
    res = RX.nfa_eval(nfa, jnp.asarray(np.array(offs, np.int32)),
                      jnp.asarray(np.frombuffer(data, np.uint8))
                      if data else jnp.zeros(1, jnp.uint8), None)
    return [bool(x) for x in np.asarray(res)]


@pytest.mark.parametrize("pattern", SUPPORTED_PATTERNS)
def test_nfa_vs_python_re(pattern):
    got = _nfa_matches(pattern, CORPUS)
    prog = re.compile(pattern)
    expect = [bool(prog.search(s)) for s in CORPUS]
    assert got == expect, (pattern,
                           [(s, g, e) for s, g, e in zip(CORPUS, got, expect)
                            if g != e])


@pytest.mark.parametrize("pattern", UNSUPPORTED_PATTERNS)
def test_unsupported_patterns_reject(pattern):
    from spark_rapids_tpu.expr import regex as RX
    with pytest.raises(RX.RegexUnsupported):
        RX.compile_pattern(pattern)


def test_rlike_end_to_end():
    session = TpuSession()
    t = pa.table({"s": CORPUS})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).filter(F.rlike(col("s"), r"^[a-z]+\d*$")),
        session, ignore_order=True)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            col("s"), F.rlike(col("s"), r"\d+\.\d+").alias("m")),
        session)


def test_rlike_unsupported_falls_back():
    session = TpuSession()
    t = pa.table({"s": ["abc", "ABC"]})
    assert_fallback_collect(
        lambda s: s.create_dataframe(t).filter(F.rlike(col("s"), r"(?i)abc")),
        session, "Filter", ignore_order=True)


def test_regexp_extract_replace_cpu():
    session = TpuSession()
    t = pa.table({"s": ["a12b", "xy", None, "c345"]})
    df = session.create_dataframe(t)
    got = df.select(F.regexp_extract(col("s"), r"([a-z])(\d+)", 2).alias("d"),
                    F.regexp_replace(col("s"), r"\d+", "#").alias("r")).to_pydict()
    assert got["d"] == ["12", "", None, "345"]
    assert got["r"] == ["a#b", "xy", None, "c#"]


def test_like_underscore_via_nfa():
    session = TpuSession()
    from spark_rapids_tpu.expr.strings import Like
    t = pa.table({"s": ["cat", "cut", "cart", "ct", None]})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            col("s"), Like(col("s"), "c_t").alias("m")),
        session)


def test_nfa_on_dict_strings_vocab_lift():
    # low-cardinality strings: regex runs over the vocab, not the rows
    session = TpuSession()
    vals = ["alpha", "beta", "gamma42"] * 50
    t = pa.table({"s": vals})
    out = session.create_dataframe(t).filter(
        F.rlike(col("s"), r"\d")).count()
    assert out == 50


# ---------------------------------------------------------------------------
# Device capture-group extraction (tagged NFA; VERDICT r3 #3)
# ---------------------------------------------------------------------------

_EXTRACT_CASES = [
    (r"(\d+)", 1),
    (r"(\d+)-(\d+)", 1),
    (r"(\d+)-(\d+)", 2),
    (r"([a-c]+)(\d*)", 2),
    (r"(a+)(a*)", 1),
    (r"v(\d+)\.(\d+)", 2),
    (r"(ab)+", 1),
    (r"(a?)(b)", 1),
    (r"x(y?)z", 1),
    (r"(\w+)\s", 1),
    (r"([0-9]{3})-([0-9]{4})", 1),
    (r"(a*)b", 1),
]


@pytest.fixture
def session():
    return TpuSession()


@pytest.mark.parametrize("pattern,group", _EXTRACT_CASES)
def test_regexp_extract_device(session, pattern, group):
    from spark_rapids_tpu.expr.strings import RegexpExtract
    e = RegexpExtract(col("s"), pattern, group)
    assert e.supported_on_tpu(), "expected device path for this pattern"
    rng = np.random.default_rng(hash(pattern) % (2**31))
    pool = ["abc123def", "12-34", "x1-2y", "", "aaa", "v10.42", "ababab",
            "b", "cb", "xyz zz", "call 555-1234 now", "aab", "a1b22c333",
            None, "hello world", "5551234", "12345"]
    vals = [pool[i] for i in rng.integers(0, len(pool), 64)]
    t = pa.table({"s": pa.array(vals, pa.string())})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            RegexpExtract(col("s"), pattern, group).alias("x")),
        session)


def test_regexp_extract_rejects_to_cpu(session):
    from spark_rapids_tpu.expr.strings import RegexpExtract
    # alternation is outside the tagged subset -> CPU fallback, still right
    e = RegexpExtract(col("s"), r"(foo|bar)x", 1)
    assert not e.supported_on_tpu()
    t = pa.table({"s": pa.array(["foox", "barx", "bazx", None])})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            RegexpExtract(col("s"), r"(foo|bar)x", 1).alias("x")),
        session)


# -- device regexp_replace (round 5: tagged-NFA span scan + byte splice) ----


_REPLACE_ROWS = ["abab", "xxabx", "", "aabb", "no match", "a1b22c333",
                 None, "aaab", "café ab café", "ababab",
                 "edge ab", "ab edge"]


@pytest.mark.parametrize("pattern,rep", [
    ("ab", "_"),            # adjacent matches
    ("[0-9]+", "N"),        # greedy class repeat
    ("a+b", "<>"),          # growing replacement
    ("b", ""),              # deletion
    ("xyz", "Q"),           # no matches anywhere
    ("^ab", "S"),           # anchored start
    ("ab*c?", "*"),         # optional tails
])
def test_regexp_replace_device(session, pattern, rep):
    from spark_rapids_tpu.expr.strings import RegexpReplace
    e = RegexpReplace(col("s"), pattern, rep)
    assert e.supported_on_tpu(), e._nfa_err
    t = pa.table({"s": pa.array(_REPLACE_ROWS, pa.string())})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            RegexpReplace(col("s"), pattern, rep).alias("x")),
        session)


def test_regexp_replace_matches_python_re(session):
    # ground truth independent of the CPU tier
    from spark_rapids_tpu.expr.strings import RegexpReplace
    t = pa.table({"s": pa.array(_REPLACE_ROWS, pa.string())})
    got = (session.create_dataframe(t)
           .select(RegexpReplace(col("s"), "a+b", "[X]").alias("x"))
           .collect().to_pylist())
    want = [None if s is None else re.sub("a+b", "[X]", s)
            for s in _REPLACE_ROWS]
    assert [r["x"] for r in got] == want


def test_regexp_replace_rejects_to_cpu(session):
    from spark_rapids_tpu.expr.strings import RegexpReplace
    # backrefs, empty-matching patterns, long replacements -> CPU tier
    for pat, rep in [("a(b)", "$1"), ("a*", "X"), ("ab", "R" * 20)]:
        e = RegexpReplace(col("s"), pat, rep)
        assert not e.supported_on_tpu(), (pat, rep)
    t = pa.table({"s": pa.array(["abab", "zz", None])})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            RegexpReplace(col("s"), "a(b)", "($1)").alias("x")),
        session)
