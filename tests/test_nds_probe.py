"""NDS harness regression: every translated query runs, matches the CPU
interpreter, and plans without device fallback (tiny SF on the CPU sim)."""
import importlib.util
import os

import pytest

_spec = importlib.util.spec_from_file_location(
    "nds_probe", os.path.join(os.path.dirname(__file__), "..", "tools",
                              "nds_probe.py"))
nds = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(nds)

from spark_rapids_tpu.sql.session import TpuSession  # noqa: E402


@pytest.fixture(scope="module")
def dfs():
    sess = TpuSession()
    tables = nds.gen_tables(0.002, seed=7)
    out = {name: sess.create_dataframe(t).cache()
           for name, t in tables.items()}
    return sess, out


# The 98-query sweep is the suite's single heaviest parametrization (~7-8min
# on the CPU sim). Tier-1 keeps the bench/probe anchors q1/q3/q6/q67/q72;
# the every-7th spread joined them until the round-18 headroom squeeze and
# now rides tools/slow_rehomed.txt (ci_check runs it), with the full sweep
# under @slow and audit_smoke's golden cost-signature replay in ci_check
# still executing all 98 against byte-identical goldens.
_ALL_QN = sorted(nds.QUERIES)
_TIER1_QN = {1, 3, 6, 67, 72} & set(_ALL_QN)


@pytest.mark.parametrize(
    "qn", [q if q in _TIER1_QN else pytest.param(q, marks=pytest.mark.slow)
           for q in _ALL_QN])
def test_nds_query(dfs, qn):
    sess, d = dfs
    df = nds.QUERIES[qn](sess, d)
    explain = df.explain()
    assert "cannot run on TPU" not in explain, explain
    assert nds._canon_rows(df.collect()) == \
        nds._canon_rows(df.collect_cpu())
