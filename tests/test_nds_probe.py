"""NDS harness regression: every translated query runs, matches the CPU
interpreter, and plans without device fallback (tiny SF on the CPU sim)."""
import importlib.util
import os

import pytest

_spec = importlib.util.spec_from_file_location(
    "nds_probe", os.path.join(os.path.dirname(__file__), "..", "tools",
                              "nds_probe.py"))
nds = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(nds)

from spark_rapids_tpu.sql.session import TpuSession  # noqa: E402


@pytest.fixture(scope="module")
def dfs():
    sess = TpuSession()
    tables = nds.gen_tables(0.002, seed=7)
    out = {name: sess.create_dataframe(t).cache()
           for name, t in tables.items()}
    return sess, out


@pytest.mark.parametrize("qn", sorted(nds.QUERIES))
def test_nds_query(dfs, qn):
    sess, d = dfs
    df = nds.QUERIES[qn](sess, d)
    explain = df.explain()
    assert "cannot run on TPU" not in explain, explain
    assert nds._canon_rows(df.collect()) == \
        nds._canon_rows(df.collect_cpu())
