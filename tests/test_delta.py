"""Delta Lake transaction-log tests: create/append/delete/update/merge
round-trips on disk with log replay, checkpoints, time travel, and
optimistic-concurrency conflicts (VERDICT r3 #6; reference delta-lake/
GpuOptimisticTransaction + command family)."""
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql.delta import (
    ConcurrentModification, DeltaTable, DeltaLog)
from spark_rapids_tpu.expr.core import col, lit


@pytest.fixture
def session():
    return TpuSession()


def _t(k, v):
    return pa.table({"k": pa.array(k, pa.int64()),
                     "v": pa.array(v, pa.float64())})


def test_create_and_read_roundtrip(session, tmp_path):
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p, _t([1, 2, 3], [1.0, 2.0, 3.0]))
    # a real _delta_log with protocol/metaData/add actions
    log0 = os.path.join(p, "_delta_log", "0" * 20 + ".json")
    actions = [json.loads(l) for l in open(log0) if l.strip()]
    kinds = {k for a in actions for k in a}
    assert {"commitInfo", "protocol", "metaData", "add"} <= kinds
    got = DeltaTable.for_path(session, p).to_df().collect().to_pylist()
    assert sorted(r["k"] for r in got) == [1, 2, 3]


def test_append_and_time_travel(session, tmp_path):
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p, _t([1], [1.0]))
    dt.append(session.create_dataframe(_t([2], [2.0])))
    dt.append(session.create_dataframe(_t([3], [3.0])))
    assert dt.to_df().count() == 3
    # time travel to version 1 (after first append)
    assert dt.to_df(version=1).count() == 2
    assert dt.to_df(version=0).count() == 1
    hist = dt.history()
    assert [h["version"] for h in hist] == [2, 1, 0]
    assert hist[-1]["operation"] == "CREATE TABLE AS SELECT"


def test_delete_copy_on_write(session, tmp_path):
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p,
                           _t(list(range(10)), [float(i) for i in range(10)]))
    n = dt.delete(col("k") >= lit(7))
    assert n == 3
    got = sorted(r["k"] for r in dt.to_df().collect().to_pylist())
    assert got == list(range(7))
    # the old file is tombstoned in the log, not referenced by HEAD
    snap = dt.log.snapshot()
    assert all(a["dataChange"] for a in snap.files.values())
    # full-table delete
    assert dt.delete() == 7
    assert dt.to_df().count() == 0


def test_update_conditional(session, tmp_path):
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p, _t([1, 2, 3, 4], [1., 2., 3., 4.]))
    n = dt.update({"v": col("v") * lit(10.0)}, col("k") > lit(2))
    assert n == 2
    got = {r["k"]: r["v"] for r in dt.to_df().collect().to_pylist()}
    assert got == {1: 1.0, 2: 2.0, 3: 30.0, 4: 40.0}


def test_merge_transactional(session, tmp_path):
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p, _t([1, 2, 3], [1., 2., 3.]))
    src = session.create_dataframe(_t([2, 3, 9], [20., 30., 90.]))
    (dt.merge(src, on=["k"])
       .when_matched_update({"v": col("__src_v")})
       .when_not_matched_insert()
       .execute())
    got = {r["k"]: r["v"] for r in dt.to_df().collect().to_pylist()}
    assert got == {1: 1.0, 2: 20.0, 3: 30.0, 9: 90.0}
    assert dt.history()[0]["operation"] == "MERGE"


def test_optimistic_concurrency_conflict(session, tmp_path):
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p, _t([1], [1.0]))
    a = DeltaTable.for_path(session, p)
    b = DeltaTable.for_path(session, p)
    snap_a = a.log.snapshot()
    snap_b = b.log.snapshot()
    a.log.commit(snap_a.version + 1, [], "WRITE")
    with pytest.raises(ConcurrentModification):
        b.log.commit(snap_b.version + 1, [], "WRITE")


def test_checkpoint_replay(session, tmp_path):
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p, _t([0], [0.0]))
    for i in range(1, 12):
        dt.append(session.create_dataframe(_t([i], [float(i)])))
    # version 10 crossed the checkpoint interval
    names = os.listdir(os.path.join(p, "_delta_log"))
    assert any(n.endswith(".checkpoint.parquet") for n in names)
    assert "_last_checkpoint" in names
    # replay from checkpoint + later commits sees everything
    assert dt.to_df().count() == 12
    # a fresh reader (checkpoint path) agrees
    dt2 = DeltaTable.for_path(session, p)
    assert dt2.to_df().count() == 12
    # and time travel BEFORE the checkpoint still replays from JSON
    assert dt2.to_df(version=3).count() == 4


def test_vacuum_drops_unreferenced(session, tmp_path):
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p, _t([1, 2], [1., 2.]))
    dt.delete(col("k") == lit(1))  # rewrites the file, tombstones old
    dropped = dt.vacuum(retain_hours=0.0)
    assert len(dropped) == 1
    assert dt.to_df().count() == 1


def test_delete_null_condition_keeps_rows(session, tmp_path):
    """DELETE only removes rows where the condition is TRUE; NULL
    evaluations keep the row (Spark DeleteCommand semantics)."""
    p = str(tmp_path / "tbl")
    t = pa.table({"k": pa.array([1, 2, None, 4], pa.int64()),
                  "v": pa.array([1., 2., 3., 4.], pa.float64())})
    dt = DeltaTable.create(session, p, t)
    n = dt.delete(col("k") >= lit(3))   # NULL >= 3 is NULL, row kept
    assert n == 1
    got = sorted(r["v"] for r in dt.to_df().collect().to_pylist())
    assert got == [1.0, 2.0, 3.0]


def test_checkpoint_is_spec_typed_schema(session, tmp_path):
    """The parquet checkpoint uses the Delta spec's typed action-struct
    columns so a foreign reader following _last_checkpoint can replay."""
    import pyarrow.parquet as pq
    p = str(tmp_path / "tbl")
    dt = DeltaTable.create(session, p, _t([0], [0.0]))
    for i in range(1, 11):
        dt.append(session.create_dataframe(_t([i], [float(i)])))
    cp = [n for n in os.listdir(os.path.join(p, "_delta_log"))
          if n.endswith(".checkpoint.parquet")]
    t = pq.read_table(os.path.join(p, "_delta_log", cp[0]))
    assert {"protocol", "metaData", "add", "remove"} <= set(t.schema.names)
    for name in ("protocol", "metaData", "add"):
        assert pa.types.is_struct(t.schema.field(name).type), name
    rows = t.to_pylist()
    assert sum(1 for r in rows if r["protocol"] is not None) == 1
    meta = next(r["metaData"] for r in rows if r["metaData"] is not None)
    assert json.loads(meta["schemaString"])["type"] == "struct"
    adds = [r["add"] for r in rows if r["add"] is not None]
    assert len(adds) == 11 and all(a["path"].endswith(".parquet")
                                   for a in adds)
