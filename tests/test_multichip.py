"""Multi-chip sharded execution (round 19): mesh validation, the
compile-cache mesh fingerprint, planner eligibility, sharded-vs-single
parity (masked rows + ANSI corners), shard-skew observability, and the
failure paths (trace-failure fallback, retry-on-OOM, cancellation).

The suite conftest forces 8 virtual CPU devices for every test process,
so these drive the REAL shard_map / all_to_all path in-process. The
heavier end-to-end gates live in tools/multichip_smoke.py (ci_check) and
tools/bench_multichip.py (MULTICHIP_r06.json).
"""
import numpy as np
import pytest

import jax

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.parallel import mesh as MESH
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSession


def _sorted(tbl):
    return tbl.sort_by([(c, "ascending") for c in tbl.column_names])


def _data(rows=4000):
    # v carries nulls so the sharded path exercises masked planes
    return {"g": [i % 23 for i in range(rows)],
            "v": [i if i % 7 else None for i in range(rows)],
            "d": [float(i % 13) * 0.5 for i in range(rows)]}


def _narrow(s, data):
    return (s.create_dataframe(data, num_partitions=8)
            .filter(col("v") % lit(5) != lit(0))
            .select(col("g"), (col("v") * lit(3)).alias("v3"),
                    (col("d") * lit(2.0)).alias("d2")))


# -- mesh construction / validation -----------------------------------------

def test_make_mesh_validates_axis_names():
    with pytest.raises(ValueError):
        MESH.make_mesh(1, axis_names=())
    with pytest.raises(ValueError):
        MESH.make_mesh(1, axis_names=("part", "part"))
    with pytest.raises(ValueError):
        MESH.make_mesh(1, axis_names=("part", 7))


def test_make_mesh_rejects_oversubscription_and_bad_dp():
    with pytest.raises(ValueError):
        MESH.make_mesh(len(jax.devices()) + 1)
    with pytest.raises(ValueError):
        MESH.make_mesh(4, dp=3)  # dp must divide n_devices
    with pytest.raises(ValueError):
        MESH.make_mesh(4, dp=2, axis_names=("part",))


def test_check_mesh_devices_raises_typed_error_on_stale(monkeypatch):
    mesh = MESH.make_mesh(2, axis_names=(MESH.PART_AXIS,))
    MESH.check_mesh_devices(mesh)  # live mesh passes
    # simulate a backend restart: device 0 leaves jax.devices()
    live = jax.devices()
    monkeypatch.setattr(MESH.jax, "devices", lambda *a: live[1:])
    with pytest.raises(MESH.MeshDeviceError):
        MESH.check_mesh_devices(mesh)


def test_multichip_devices_clamps():
    s_all = TpuSession({C.MULTICHIP_ENABLED.key: "true"})
    assert MESH.multichip_devices(s_all.conf) == len(jax.devices())
    s_big = TpuSession({C.MULTICHIP_ENABLED.key: "true",
                        C.MULTICHIP_DEVICES.key: 10_000})
    assert MESH.multichip_devices(s_big.conf) == len(jax.devices())
    s_two = TpuSession({C.MULTICHIP_ENABLED.key: "true",
                        C.MULTICHIP_DEVICES.key: 2})
    assert MESH.multichip_devices(s_two.conf) == 2


# -- compile-cache fingerprint isolation ------------------------------------

def test_compile_fingerprint_isolates_mesh_shape():
    from spark_rapids_tpu.runtime.compile_cache import _fp_of
    off = TpuSession({}).conf
    on2 = TpuSession({C.MULTICHIP_ENABLED.key: "true",
                      C.MULTICHIP_DEVICES.key: 2}).conf
    on8 = TpuSession({C.MULTICHIP_ENABLED.key: "true",
                      C.MULTICHIP_DEVICES.key: 8}).conf
    assert _fp_of(on2) != _fp_of(on8)
    # disabled conf keeps the pre-multichip fingerprint: no mesh component
    assert not any("mesh" in str(part) for part in _fp_of(off))
    assert _fp_of(off) != _fp_of(on8)


# -- planner eligibility ----------------------------------------------------

def test_planner_shards_narrow_chain():
    s = TpuSession({C.MULTICHIP_ENABLED.key: "true"})
    out = _narrow(s, _data(2000)).collect()
    assert out.num_rows > 0
    assert "ShardedStageExec" in s._last_exec.tree_string()
    snaps = s.last_metrics()
    assert sum(v.get("shardWaves", 0) for v in snaps.values()) >= 1


def test_fallback_reasons_cover_wide_types_and_carry():
    from spark_rapids_tpu.exec import sharded as SH

    class _Field:
        def __init__(self, name, dtype):
            self.name, self.dtype = name, dtype

    class _Schema:
        def __init__(self, fields):
            self.fields = fields

    class _Body:
        has_carry = False
        exhausts = False
        name = "project"
        key = ("stage",)

    class _Child:
        schema = _Schema([_Field("s", T.StringType())])

    class _Node:
        bodies = [_Body()]
        members = []
        children = [_Child()]

    reason = SH._fallback_reason(_Node())
    assert reason is not None and "StringType" in reason

    class _CarryBody(_Body):
        has_carry = True
        name = "limit"

    class _CarryNode(_Node):
        bodies = [_CarryBody()]

    reason = SH._fallback_reason(_CarryNode())
    assert reason is not None and "loop state" in reason

    class _IntChild:
        schema = _Schema([_Field("v", T.Int64Type())])

    class _OkNode(_Node):
        children = [_IntChild()]

    assert SH._fallback_reason(_OkNode()) is None


# -- parity: sharded results byte-identical to single-device ----------------

@pytest.mark.parametrize("ansi", ["false", "true"])
def test_sharded_parity_masked_and_ansi(ansi):
    data = _data(3000)
    outs = {}
    for flag in ("true", "false"):
        s = TpuSession({C.MULTICHIP_ENABLED.key: flag,
                        C.ANSI_ENABLED.key: ansi})
        outs[flag] = _sorted(_narrow(s, data).collect())
        engaged = "ShardedStageExec" in s._last_exec.tree_string()
        assert engaged == (flag == "true")
    assert outs["true"].equals(outs["false"])


def test_shuffle_agg_parity_and_ici_metric():
    data = _data(3000)
    outs = {}
    for flag in ("true", "false"):
        s = TpuSession({C.MULTICHIP_ENABLED.key: flag})
        df = (s.create_dataframe(data, num_partitions=8)
              .group_by(col("g")).agg(F.sum("v").alias("sv"),
                                      F.count().alias("n")))
        outs[flag] = _sorted(df.collect())
        ici = sum(v.get("iciExchangeTime", 0)
                  for v in s.last_metrics().values())
        assert (ici > 0) == (flag == "true")
    assert outs["true"].equals(outs["false"])


# -- shard-skew observability -----------------------------------------------

def test_resolve_shards_folds_skew():
    from spark_rapids_tpu.analysis.kernel_audit import _resolve_shards
    doc = _resolve_shards([(4, np.array([100, 300, 100, 100])),
                           (4, np.array([100, 100, 100, 100]))])
    assert doc["n_shards"] == 4
    assert doc["waves"] == 2
    assert doc["rows_per_shard"] == [200, 400, 200, 200]
    assert doc["skew"] == 1.6  # 400 / mean(250)
    assert _resolve_shards([]) is None


def test_roofline_reports_seeded_skew():
    s = TpuSession({C.MULTICHIP_ENABLED.key: "true",
                    C.OBS_AUDIT_ENABLED.key: "true"})
    rows = 4000
    # round-robin partitioning + a v-range filter concentrates the
    # surviving rows in a value band, not a partition: instead seed skew
    # through filter selectivity that differs across the g stripes the
    # 8 partitions receive
    data = {"g": [i % 8 for i in range(rows)],
            "v": list(range(rows))}
    df = (s.create_dataframe(data, num_partitions=8)
          .filter(col("v") % lit(8) == lit(0))
          .select(col("g"), (col("v") + lit(1)).alias("v1")))
    df.collect()
    roof = s.last_roofline()
    shards = (roof or {}).get("shards")
    assert shards is not None
    assert shards["n_shards"] == 8
    assert shards["waves"] >= 1
    assert len(shards["rows_per_shard"]) == 8
    assert shards["skew"] >= 1.0


# -- failure paths ----------------------------------------------------------

def test_trace_failure_falls_back_to_single_device(monkeypatch):
    from spark_rapids_tpu.exec import fuse
    data = _data(2000)
    expect = _sorted(_narrow(TpuSession({}), data).collect())

    orig = fuse.fused

    def boom(key, builder):
        if key and key[0] == "sharded_stage":
            raise RuntimeError("synthetic shard_map trace failure")
        return orig(key, builder)

    monkeypatch.setattr(fuse, "fused", boom)
    s = TpuSession({C.MULTICHIP_ENABLED.key: "true"})
    got = _sorted(_narrow(s, data).collect())
    assert got.equals(expect)  # per-slot replay through the fused path


def test_wave_retry_on_injected_oom():
    from spark_rapids_tpu.runtime.retry import OomInjector, set_backoff
    data = _data(2000)
    expect = _sorted(_narrow(TpuSession({}), data).collect())
    s = TpuSession({C.MULTICHIP_ENABLED.key: "true"})
    set_backoff(0.0, 0.0)
    OomInjector.configure(num_ooms=1)
    try:
        got = _sorted(_narrow(s, data).collect())
    finally:
        OomInjector.configure(num_ooms=0)
    assert got.equals(expect)
    assert "ShardedStageExec" in s._last_exec.tree_string()


def test_cancellation_not_swallowed_by_fallback(monkeypatch):
    from spark_rapids_tpu.exec import fuse
    from spark_rapids_tpu.runtime.lifecycle import QueryCancelledError

    orig = fuse.fused

    def cancelled(key, builder):
        if key and key[0] == "sharded_stage":
            raise QueryCancelledError("cancelled by user")
        return orig(key, builder)

    monkeypatch.setattr(fuse, "fused", cancelled)
    s = TpuSession({C.MULTICHIP_ENABLED.key: "true"})
    with pytest.raises(QueryCancelledError):
        _narrow(s, _data(1000)).collect()
