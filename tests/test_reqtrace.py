"""Distributed request tracing (runtime/obs/reqtrace.py): W3C context
round-trip, the serving<->exec span join, the tail-sampling verdict
matrix, metric exemplars on /metrics, and the multi-replica fleet view
(tools/fleet_report.py) over a shared historyDir.
"""
import http.client
import json
import os
import sys

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.runtime import serving
from spark_rapids_tpu.runtime.obs import reqtrace
from spark_rapids_tpu.runtime.obs.history import QueryHistoryStore
from spark_rapids_tpu.runtime.obs.registry import MetricsRegistry
from spark_rapids_tpu.sql.session import TpuSession

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import fleet_report  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Reqtrace rides the serving/obs singletons; each test gets fresh
    ones (the reqtrace recorder itself is reset by conftest)."""
    from spark_rapids_tpu.runtime import obs
    obs.shutdown_for_tests()
    yield
    obs.shutdown_for_tests()


def _table(n=500, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 8, n),
                     "v": rng.integers(1, 1000, n)})


def _serving_session(**extra):
    conf = {"spark.rapids.serving.enabled": "true"}
    conf.update(extra)
    s = TpuSession(conf)
    s.create_or_replace_temp_view("t", s.create_dataframe(_table()))
    return s


_SQL = "SELECT k, SUM(v) AS sv FROM t GROUP BY k ORDER BY k"
_TID = "ab" * 16
_TP = f"00-{_TID}-{'cd' * 8}-01"


# ---------------------------------------------------------------------------
# W3C traceparent round-trip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("header", [
    None, "", "garbage", "00-abc-def-01",
    f"00-{'0' * 32}-{'cd' * 8}-01",      # all-zero trace id
    f"00-{_TID}-{'0' * 16}-01",          # all-zero parent span
    f"ff-{_TID}-{'cd' * 8}-01",          # forbidden version
    f"00-{'xy' * 16}-{'cd' * 8}-01",     # non-hex
    f"00-{_TID}-{'cd' * 8}",             # missing field
])
def test_malformed_traceparent_mints(header):
    assert reqtrace.parse_traceparent(header) is None
    ctx = reqtrace.RequestContext(64, "r1", traceparent=header)
    assert not ctx.honored and ctx.parent_span_id is None
    assert len(ctx.trace_id) == 32 and int(ctx.trace_id, 16) >= 0
    assert ctx.trace_id != _TID


def test_valid_traceparent_honored_and_propagated():
    assert reqtrace.parse_traceparent(_TP) == (_TID, "cd" * 8, "01")
    ctx = reqtrace.RequestContext(64, "r1", traceparent=_TP)
    assert ctx.honored and ctx.trace_id == _TID
    assert ctx.parent_span_id == "cd" * 8
    # the OUTGOING header keeps the trace id but parents on this
    # request's own root span (a fresh 16-hex id)
    out = ctx.traceparent()
    assert out.startswith(f"00-{_TID}-") and out.endswith("-01")
    assert out.split("-")[2] == ctx.span_id != "cd" * 8


def test_http_traceparent_roundtrip(tmp_path):
    """POST /sql honors an incoming traceparent header and answers with
    the outgoing one; absent a header the server mints a fresh trace."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    reqtrace.install(out_dir=str(tmp_path), sample_ratio=0.0)
    _serving_session(**{"spark.rapids.obs.port": str(port)})
    from spark_rapids_tpu.runtime import obs
    port = obs.state().server.port

    def post(headers):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/sql", body=json.dumps({"sql": _SQL}),
                     headers=dict({"Content-Type": "application/json"},
                                  **headers))
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        hdr = resp.getheader("traceparent")
        conn.close()
        return doc, hdr

    doc, hdr = post({"traceparent": _TP})
    assert doc["trace_id"] == _TID
    assert hdr == doc["traceparent"]
    assert hdr.startswith(f"00-{_TID}-") and hdr.endswith("-01")
    doc2, hdr2 = post({})
    assert len(doc2["trace_id"]) == 32 and doc2["trace_id"] != _TID
    assert hdr2.startswith(f"00-{doc2['trace_id']}-")


# ---------------------------------------------------------------------------
# the serving<->exec span join in an exported timeline
# ---------------------------------------------------------------------------

def test_export_joins_serving_and_exec_spans(tmp_path):
    rec = reqtrace.install(out_dir=str(tmp_path), sample_ratio=1.0,
                           min_interval_s=0.0, replica_id="repl-a")
    _serving_session()
    code, doc = serving.handle_sql({"sql": _SQL})
    assert code == 200 and doc["status"] == "ok"
    assert doc["replica_id"] == "repl-a"
    rt = doc["reqtrace"]
    assert rt["verdict"] == "sampled" and os.path.exists(rt["path"])
    timeline = json.load(open(rt["path"]))
    meta = timeline["otherData"]
    assert meta["trace_id"] == doc["trace_id"]
    assert meta["replica_id"] == "repl-a"
    events = timeline["traceEvents"]
    serving_spans = {e["name"] for e in events
                     if e.get("cat") == "serving"}
    # the serving layer's own span tree
    assert {"intake", "cache_lookup", "execute",
            "serialize"} <= serving_spans
    # joined engine exec spans: the epilogue stamped the request's
    # query id, and engine events in the ring carry the same id
    qid = meta["query_id"]
    assert isinstance(qid, int)
    engine = [e for e in events if e.get("cat") not in ("serving", None)
              and (e.get("args") or {}).get("query_id") == qid]
    assert engine, "no engine exec spans joined to the request's query"
    # the OTLP sibling parents serving phases on the request root
    otlp = json.load(open(rt["path"][:-5] + ".otlp.json"))
    spans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
    root = next(s for s in spans if s["name"] == "POST /sql")
    assert root["traceId"] == doc["trace_id"]
    intake = next(s for s in spans if s["name"] == "intake")
    assert intake["parentSpanId"] == root["spanId"]
    assert rec.exports == 1


def test_cache_hit_timeline_and_history_trace_id(tmp_path):
    hist = tmp_path / "hist"
    reqtrace.install(out_dir=str(tmp_path / "rt"), sample_ratio=1.0,
                     min_interval_s=0.0)
    _serving_session(**{"spark.rapids.obs.historyDir": str(hist)})
    _, d1 = serving.handle_sql({"sql": _SQL})
    code, d2 = serving.handle_sql({"sql": _SQL})
    assert code == 200 and d2["cache"] == "hit"
    assert d2["reqtrace"]["verdict"] == "sampled"
    timeline = json.load(open(d2["reqtrace"]["path"]))
    names = {e["name"] for e in timeline["traceEvents"]
             if e.get("cat") == "serving"}
    assert "cache_lookup" in names and "execute" not in names
    # the history store carries each request's trace id (the fleet
    # view's join key back to the exported timelines)
    recs = QueryHistoryStore(str(hist)).read_all()
    by_type = {}
    for r in recs:
        by_type.setdefault(r["type"], []).append(r)
    assert by_type["query"][-1]["trace_id"] == d1["trace_id"]
    assert by_type["result_cache_hit"][-1]["trace_id"] == d2["trace_id"]


# ---------------------------------------------------------------------------
# the tail-sampling verdict matrix
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw,verdict", [
    (dict(status="failed"), "error"),
    (dict(status="failed", slo_breach=True), "error"),  # precedence
    (dict(status="cancelled", cancel_reason="user"), "cancelled"),
    (dict(status="cancelled", cancel_reason="deadline"), "deadline"),
    (dict(status="ok", slo_breach=True), "slo_breach"),
    (dict(status="ok", slow_vs_baseline=True), "slow_vs_baseline"),
    (dict(status="ok", slo_breach=True, slow_vs_baseline=True),
     "slo_breach"),
    (dict(status="ok", draw=0.001), "sampled"),
    (dict(status="ok", draw=0.999), "dropped"),
    (dict(status="bad_request", draw=0.001), "sampled"),
])
def test_verdict_matrix(tmp_path, kw, verdict):
    rec = reqtrace.ReqTraceRecorder(out_dir=str(tmp_path),
                                    sample_ratio=0.01)
    assert rec.decide(**kw) == verdict


def test_verdict_ratio_edges_and_export_bookkeeping(tmp_path):
    # ratio 0: nothing ordinary ever keeps, even draw == 0
    rec = reqtrace.ReqTraceRecorder(out_dir=str(tmp_path),
                                    sample_ratio=0.0)
    assert rec.decide(status="ok", draw=0.0) == "dropped"
    # ratio 1: everything ordinary keeps
    rec = reqtrace.ReqTraceRecorder(out_dir=str(tmp_path),
                                    sample_ratio=1.0, min_interval_s=0.0)
    assert rec.decide(status="ok", draw=0.999999) == "sampled"
    # end(): dropped rings write nothing; kept rings write the pair
    ctx = rec.begin()
    out = rec.end(ctx, status="failed", error="Boom")
    assert out["kept"] and out["verdict"] == "error"
    assert os.path.exists(out["path"])
    assert os.path.exists(out["otlp_path"])
    assert json.load(open(out["path"]))["otherData"]["error"] == "Boom"
    rec2 = reqtrace.ReqTraceRecorder(out_dir=str(tmp_path / "none"),
                                     sample_ratio=0.0)
    ctx2 = rec2.begin()
    out2 = rec2.end(ctx2, status="ok")
    assert not out2["kept"] and out2["path"] is None
    assert not os.path.exists(str(tmp_path / "none"))
    assert rec2.dropped == 1


def test_sampled_exports_rate_limited_but_errors_never(tmp_path):
    rec = reqtrace.ReqTraceRecorder(out_dir=str(tmp_path),
                                    sample_ratio=1.0,
                                    min_interval_s=3600.0)
    assert rec.end(rec.begin(), status="ok", draw=0.0)["path"]
    # within the interval: a sampled keep is rate-limited away...
    out = rec.end(rec.begin(), status="ok", draw=0.0)
    assert out["kept"] and out["path"] is None
    assert rec.rate_limited == 1
    # ...but an always-keep verdict bypasses the interval
    assert rec.end(rec.begin(), status="failed")["path"]


# ---------------------------------------------------------------------------
# exemplars on /metrics
# ---------------------------------------------------------------------------

def test_exemplar_renders_openmetrics_bucket_lines():
    reg = MetricsRegistry()
    h = reg.histogram("rapids_serving_request_ms", "request wall")
    h.observe(3.0)
    h.observe(12.5, exemplar={"trace_id": "deadbeef" * 4})
    out = reg.render_prometheus()
    bucket_lines = [ln for ln in out.splitlines()
                    if ln.startswith("rapids_serving_request_ms_bucket")]
    assert bucket_lines and bucket_lines[-1].count('le="+Inf"') == 1
    ex_lines = [ln for ln in bucket_lines if " # {" in ln]
    assert len(ex_lines) == 1
    assert 'trace_id="' + "deadbeef" * 4 + '"' in ex_lines[0]
    # cumulative counts are monotone and end at the total
    counts = [int(ln.split(" # ")[0].rsplit(" ", 1)[1])
              for ln in bucket_lines]
    assert counts == sorted(counts) and counts[-1] == 2


def test_serving_request_records_resolvable_exemplar(tmp_path):
    reqtrace.install(out_dir=str(tmp_path), sample_ratio=1.0,
                     min_interval_s=0.0)
    _serving_session()
    code, doc = serving.handle_sql({"sql": _SQL})
    assert code == 200
    from spark_rapids_tpu.runtime import obs
    out = obs.state().registry.render_prometheus()
    ex_lines = [ln for ln in out.splitlines()
                if ln.startswith("rapids_serving_request_ms_bucket")
                and " # {" in ln]
    assert ex_lines, "serving latency histogram carries no exemplar"
    assert f'trace_id="{doc["trace_id"]}"' in ex_lines[0]
    # the exemplar resolves to the exported per-request timeline
    path = ex_lines[0].split('path="')[1].split('"')[0]
    assert path == doc["reqtrace"]["path"] and os.path.exists(path)


# ---------------------------------------------------------------------------
# the fleet view over a shared historyDir
# ---------------------------------------------------------------------------

def _fleet_record(replica, digest, wall_ms, trace_id, status="ok",
                  compile_s=0.0, slo=None):
    rec = {"type": "query", "replica_id": replica, "plan_digest": digest,
           "duration_ns": int(wall_ms * 1e6), "status": status,
           "trace_id": trace_id,
           "attribution": {"buckets": {"compile": compile_s}}}
    if slo is not None:
        rec["slo_breach"] = slo
    return rec


def test_two_replica_fleet_report_merge(tmp_path):
    """Two replicas appending to ONE historyDir: the fleet summary
    splits each digest per replica, flags cross-replica p99 skew, and
    joins reqtrace artifacts back to history trace ids."""
    hist = str(tmp_path / "hist")
    a = QueryHistoryStore(hist)   # replica A's handle
    b = QueryHistoryStore(hist)   # replica B's handle on the SAME dir
    tid_a = "aa" * 16
    tid_b = "bb" * 16
    for w in (10.0, 11.0, 12.0):
        a.append(_fleet_record("repl-a", "digX", w, tid_a,
                               compile_s=0.5))
    for w in (40.0, 44.0, 48.0):
        b.append(_fleet_record("repl-b", "digX", w, tid_b, slo={"x": 1}))
    b.append(_fleet_record("repl-b", "digY", 5.0, "cc" * 16,
                           status="failed"))
    b.append({"type": "result_cache_hit", "replica_id": "repl-b",
              "plan_digest": "digX", "wall_ms": 1.0, "trace_id": tid_b})
    rt = tmp_path / "rt"
    rt.mkdir()
    (rt / f"req_00001_slo_breach_{tid_b[:8]}.json").write_text("{}")
    (rt / "req_00002_error_99999999.json").write_text("{}")

    doc = fleet_report.fleet_summary(
        QueryHistoryStore(hist).read_all(),
        reqtrace_dirs=[str(rt)], skew_factor=1.5)
    assert doc["replicas"] == ["repl-a", "repl-b"]
    assert doc["totals"]["repl-a"]["queries"] == 3
    assert doc["totals"]["repl-b"]["slo_breaches"] == 3
    assert doc["totals"]["repl-b"]["failed"] == 1
    assert doc["totals"]["repl-b"]["cache_hits"] == 1
    # the per-digest split keeps the replicas separate
    cell = doc["digests"]["digX"]
    assert cell["repl-a"]["runs"] == 3 and cell["repl-b"]["runs"] == 3
    assert cell["repl-a"]["compile_s"] == 1.5
    assert cell["repl-a"]["p99_ms"] == 12.0
    assert cell["repl-b"]["p99_ms"] == 48.0
    assert tid_a in cell["repl-a"]["trace_ids"]
    # digX is skewed 4x between the replicas; digY ran on one only
    assert [s["plan_digest"] for s in doc["skewed"]] == ["digX"]
    assert doc["skewed"][0]["slow"] == "repl-b"
    assert doc["skewed"][0]["ratio"] == 4.0
    # artifact join: B's timeline resolves, the orphan reports itself
    arts = {a["file"].rsplit("/", 1)[-1]: a for a in doc["reqtrace"]}
    assert arts[f"req_00001_slo_breach_{tid_b[:8]}.json"][
        "trace_id"] == tid_b
    assert arts["req_00002_error_99999999.json"]["trace_id"] is None
    text = fleet_report.render_text(doc)
    assert "repl-a" in text and "skew" in text and "slo_breach" in text


def test_fleet_report_cli_json(tmp_path, capsys):
    hist = str(tmp_path / "hist")
    QueryHistoryStore(hist).append(
        _fleet_record("r1", "d", 3.0, "ee" * 16))
    sys_argv = sys.argv
    sys.argv = ["fleet_report.py", hist, "--json"]
    try:
        assert fleet_report.main() == 0
    finally:
        sys.argv = sys_argv
    doc = json.loads(capsys.readouterr().out)
    assert doc["replicas"] == ["r1"]
