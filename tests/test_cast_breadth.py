"""Device cast breadth: string <-> float/date/timestamp
(reference GpuCast.scala + jni CastStrings)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.expr.core import col

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


def test_string_to_double(session):
    vals = ["1.5", "-2", "+3.25", "1e3", "2.5E-2", "-1.25e+2", ".5", "5.",
            "  42  ", "", "abc", "1.2.3", "1e", "e5", None, "Infinity",
            "-Infinity", "NaN", "0", "-0.0", "123456789012345678901",
            "9e99", "1e-300", "0.000001"]
    t = {"s": pa.array(vals)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            col("s").cast(T.FLOAT64).alias("d")),
        session, approx_float=1e-13)


def test_string_to_date(session):
    vals = ["2020-01-15", "1999-12-31", "2020-1-5", "1970-01-01",
            " 2023-06-30 ", "2020-02-29", "2019-02-29", "2020-13-01",
            "2020-00-10", "2020-01-32", "not-a-date", "", None, "2020",
            "2020-07", "0001-01-01", "9999-12-31", "2020-01-15-"]
    t = {"s": pa.array(vals)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            col("s").cast(T.DATE).alias("d")),
        session)


def test_string_to_timestamp(session):
    vals = ["2020-01-15 10:30:45", "2020-01-15T23:59:59.123456",
            "2020-01-15", "1969-12-31 23:59:59.5", "2020-01-15 10:30",
            "2020-01-15 24:00:00", "2020-01-15 10:61:00", "garbage", None,
            "1970-01-01 00:00:00", "2020-6-5 1:2:3", "2020-01-15 10:30:45.1"]
    t = {"s": pa.array(vals)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            col("s").cast(T.TIMESTAMP).alias("ts")),
        session)


def test_date_timestamp_to_string(session):
    import datetime
    dates = [datetime.date(2020, 1, 15), datetime.date(1969, 7, 20),
             datetime.date(1, 1, 1), datetime.date(9999, 12, 31), None]
    tss = [datetime.datetime(2020, 1, 15, 10, 30, 45),
           datetime.datetime(2020, 1, 15, 10, 30, 45, 123456),
           datetime.datetime(2020, 1, 15, 10, 30, 45, 500000),
           datetime.datetime(1970, 1, 1), None]
    t = {"d": pa.array(dates, pa.date32()),
         "ts": pa.array(tss, pa.timestamp("us"))}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            col("d").cast(T.STRING).alias("ds"),
            col("ts").cast(T.STRING).alias("tss")),
        session)


def test_cast_roundtrip_generated(session):
    from data_gen import DateGen, TimestampGen, DoubleGen, gen_df
    spec = [("d", DateGen()), ("ts", TimestampGen()),
            ("f", DoubleGen(min_val=-1e9, max_val=1e9))]
    # render then reparse: exact round trip on device
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=512, seed=107).select(
            col("d").cast(T.STRING).cast(T.DATE).alias("d2"),
            col("ts").cast(T.STRING).cast(T.TIMESTAMP).alias("ts2")),
        session)
