"""Differential join tests (reference join_test.py)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


LEFT = {
    "k": pa.array([1, 2, 3, 4, None, 2, 7], pa.int64()),
    "ks": pa.array(["a", "b", "c", None, "e", "b", "g"]),
    "lv": pa.array([10, 20, 30, 40, 50, 60, 70], pa.int32()),
}
RIGHT = {
    "k": pa.array([2, 3, 3, 5, None, 2], pa.int64()),
    "ks": pa.array(["b", "c", "x", "e", None, "b"]),
    "rv": pa.array([200.5, 300.25, 301.0, None, 500.0, 201.75]),
}


def dfs(s, parts=1):
    return (s.create_dataframe(dict(LEFT), num_partitions=parts),
            s.create_dataframe(dict(RIGHT), num_partitions=1))


ALL_HOW = ["inner", "left", "right", "full", "left_semi", "left_anti"]


@pytest.mark.parametrize("how", ALL_HOW)
def test_join_int_key(session, how):
    def q(s):
        l, r = dfs(s)
        return l.join(r, on=[(col("k"), col("k"))], how=how)
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_join_string_key(session, how):
    def q(s):
        l, r = dfs(s)
        return l.join(r, on=[(col("ks"), col("ks"))], how=how)
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_join_multi_key(session):
    def q(s):
        l, r = dfs(s)
        return l.join(r, on=[(col("k"), col("k")), (col("ks"), col("ks"))],
                      how="inner")
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_join_multi_partition_probe(session):
    def q(s):
        l, r = dfs(s, parts=3)
        return l.join(r, on=[(col("k"), col("k"))], how="inner")
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_join_with_condition(session):
    def q(s):
        l, r = dfs(s)
        return l.join(r, on=[(col("k"), col("k"))], how="inner",
                      ).filter(col("lv") > lit(20))
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_join_ast_condition(session):
    """Extra non-equi condition evaluated on joined pairs (reference
    conditional joins via cudf AST)."""
    from spark_rapids_tpu.plan import nodes as P

    def q(s):
        l, r = dfs(s)
        plan = P.Join(l.plan, r.plan, [col("k")], [col("k")], "left",
                      condition=col("rv") > lit(201.0))
        from spark_rapids_tpu.sql.dataframe import DataFrame
        return DataFrame(plan, s)
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_cross_join(session):
    def q(s):
        l, r = dfs(s)
        return l.select(col("k").alias("lk")).limit(3).join(
            r.select(col("k").alias("rk")).limit(2), how="cross")
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_self_join_dedupe_on(session):
    def q(s):
        l, r = dfs(s)
        return l.join(r, on="k", how="inner")
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_join_empty_build(session):
    def q(s):
        l, r = dfs(s)
        return l.join(r.filter(col("rv") > lit(1e9)),
                      on=[(col("k"), col("k"))], how="left")
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_join_then_agg(session):
    def q(s):
        l, r = dfs(s)
        return (l.join(r, on=[(col("k"), col("k"))], how="inner")
                .group_by(col("lv")).agg(F.sum("rv").alias("srv")))
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


# -- adaptive join strategy (AQE analog) -------------------------------------

def test_adaptive_join_picks_broadcast_for_small_build(session):
    # build side behind an aggregate: no planner estimate -> adaptive;
    # measured count is tiny -> broadcast at runtime
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.exec import tpu_nodes as X

    def q(s):
        left = s.create_dataframe(
            {"k": list(range(200)), "v": list(range(200))}, num_partitions=3)
        right = s.create_dataframe(
            {"k": [1, 2, 3, 1], "w": [10, 20, 30, 40]})
        rsmall = right.group_by(col("k")).agg(F.sum("w").alias("sw"))
        return left.join(rsmall, on="k", how="inner")

    df = q(session)
    root, _ = convert_plan(df.plan, session.conf)
    nodes = []
    def walk(e):
        nodes.append(e)
        for c in e.children:
            walk(c)
    walk(root)
    adaptive = [n for n in nodes if isinstance(n, X.AdaptiveJoinExec)]
    assert adaptive, [type(n).__name__ for n in nodes]
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)
    from spark_rapids_tpu.runtime.task import TaskContext
    for p in range(root.num_partitions):
        with TaskContext(partition_id=p) as c:
            list(root.execute_partition(c, p))
    assert isinstance(adaptive[0]._chosen, X.BroadcastHashJoinExec)


def test_adaptive_join_shuffles_large_build():
    from spark_rapids_tpu.sql.session import TpuSession
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.exec import tpu_nodes as X
    s = TpuSession({"spark.rapids.sql.join.broadcastRowThreshold": 8})

    def q(ss):
        left = ss.create_dataframe(
            {"k": [i % 40 for i in range(300)], "v": list(range(300))},
            num_partitions=3)
        right = ss.create_dataframe(
            {"k": list(range(40)), "w": list(range(40))}, num_partitions=2)
        rbig = right.group_by(col("k")).agg(F.sum("w").alias("sw"))
        return left.join(rbig, on="k", how="left")

    df = q(s)
    root, _ = convert_plan(df.plan, s.conf)
    nodes = []
    def walk(e):
        nodes.append(e)
        for c in e.children:
            walk(c)
    walk(root)
    adaptive = [n for n in nodes if isinstance(n, X.AdaptiveJoinExec)]
    assert adaptive
    assert_tpu_and_cpu_are_equal_collect(q, s, ignore_order=True)
    from spark_rapids_tpu.runtime.task import TaskContext
    for p in range(root.num_partitions):
        with TaskContext(partition_id=p) as c:
            list(root.execute_partition(c, p))
    assert isinstance(adaptive[0]._chosen, X.ShuffledHashJoinExec)
