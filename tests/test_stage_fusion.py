"""Whole-stage vertical fusion (spark.rapids.sql.stageFusion.enabled).

Dispatch-budget regression tests (extending PR 1's partitionDispatches
counters with the fuse-layer dispatch hook): a fused Filter→Project→
partial-HashAggregate chain must issue exactly ONE device dispatch per
input batch, and fused results must be identical to the unfused chain
across ANSI on/off, masked batches, and empty batches. Plus the satellite
regressions riding this PR (process-wide host pool, CoalesceBatchesExec
metrics).
"""
import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar.batch import to_arrow
from spark_rapids_tpu.expr.core import SparkException, col, lit
from spark_rapids_tpu.plan import nodes as P
from spark_rapids_tpu.plan.overrides import convert_plan
from spark_rapids_tpu.exec import fuse
from spark_rapids_tpu.exec import tpu_nodes as X
from spark_rapids_tpu.exec.stage_fusion import fuse_stages, fused_stage_cls
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime.task import TaskContext
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSession

from asserts import assert_tpu_and_cpu_are_equal_collect
from data_gen import (
    DoubleGen, IntegerGen, LongGen, RepeatSeqGen, StringGen, gen_df,
)


FusedStageExec = fused_stage_cls()

_SPEC = [("k", RepeatSeqGen(IntegerGen(min_val=0, max_val=40), length=30)),
         ("v", LongGen(min_val=-(1 << 40), max_val=1 << 40)),
         ("d", DoubleGen()),
         ("s", StringGen())]


@pytest.fixture
def session():
    return TpuSession()


def _drain(ex, names):
    parts = []
    for p in range(ex.num_partitions):
        with TaskContext(partition_id=p) as ctx:
            for b in ex.execute_partition(ctx, p):
                parts.extend(to_arrow(b, names).to_pylist())
    return parts


def _eq(a, b):
    if isinstance(a, float) and isinstance(b, float):
        return (math.isnan(a) and math.isnan(b)) or a == b
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, dict):
        return set(a) == set(b) and all(_eq(a[k], b[k]) for k in a)
    return a == b


class _DispatchCounter:
    """Counts device dispatches through the fuse layer (fused() entries +
    compiled.run_stage), the budget the fusion pass minimizes."""

    def __init__(self):
        self.keys = []

    def __enter__(self):
        fuse.set_dispatch_hook(self.keys.append)
        return self

    def __exit__(self, *exc):
        fuse.set_dispatch_hook(None)
        return False

    @property
    def count(self):
        return len(self.keys)


def _chain_df(s, length=1800, parts=3, masked=False):
    df = gen_df(s, _SPEC, length=length, seed=11, num_partitions=parts)
    if masked:
        # a leading filter makes every chain input a selection-mask batch
        df = df.filter(col("s").is_not_null())
    return (df.filter(col("v").is_not_null() & (col("v") > lit(0)))
            .select(col("k"), (col("v") % lit(1000)).alias("m"),
                    (col("d") * lit(2.0)).alias("dd")))


# ---------------------------------------------------------------------------
# Plan shape
# ---------------------------------------------------------------------------

def test_chain_collapses_to_fused_stage(session):
    df = _chain_df(session)
    root, _ = convert_plan(df.plan, session.conf)
    assert isinstance(root, FusedStageExec)
    kinds = [type(m).__name__ for m in root.members]
    assert kinds == ["FilterExec", "ProjectExec"]  # child-most first


def test_fusion_disabled_keeps_chain():
    s = TpuSession({"spark.rapids.sql.stageFusion.enabled": "false"})
    df = _chain_df(s)
    root, _ = convert_plan(df.plan, s.conf)
    assert isinstance(root, X.ProjectExec)
    assert isinstance(root.children[0], X.FilterExec)


def test_single_dispatching_op_not_fused(session):
    df = gen_df(session, _SPEC, length=300, seed=3).filter(col("v") > lit(0))
    root, _ = convert_plan(df.plan, session.conf)
    assert isinstance(root, X.FilterExec)  # one op = already one dispatch


def test_explain_stages_prints_fusion_groups(session, capsys):
    df = _chain_df(session)
    s = df.explain(mode="stages")
    capsys.readouterr()
    assert "*(1)" in s and "FusedStageExec" in s and "[fused]" in s


# ---------------------------------------------------------------------------
# Dispatch budget
# ---------------------------------------------------------------------------

def _partial_agg_chain(s, n_rows=3000, parts=3):
    """Filter→Project→partial-HashAggregate over a NON-packable (float)
    group key, built the way the multi-device planner shapes it."""
    rng = np.random.default_rng(5)
    t = pa.table({
        "g": rng.uniform(0, 6, n_rows).round(0),
        "v": rng.integers(-1000, 1000, n_rows),
        "d": rng.uniform(-10, 10, n_rows),
    })
    df = (s.create_dataframe(t, num_partitions=parts)
          .filter(col("v") > lit(-500))
          .select(col("g"), (col("v") * lit(3)).alias("v3"), col("d"))
          .group_by(col("g")).agg(F.sum("v3").alias("sv"),
                                  F.count().alias("n"),
                                  F.min("d").alias("md")))
    node = df.plan
    while not isinstance(node, P.Aggregate):
        node = node.children[0]
    child, _ = convert_plan(node.children[0], s.conf)
    agg = X.HashAggregateExec(node, [child], s.conf, mode="partial")
    return fuse_stages(agg, s.conf), df


def test_partial_agg_chain_absorbed_one_dispatch_per_batch(session):
    root, _ = _partial_agg_chain(session)
    assert root.pre_chain is not None
    assert [type(m).__name__ for m in root.pre_chain_members] == \
        ["FilterExec", "ProjectExec"]
    with _DispatchCounter() as dc:
        rows = _drain(root, [f.name for f in root.state_fields()])
    assert rows
    # THE acceptance assertion: one input batch per source partition, ONE
    # composed dispatch each — nothing else touches the device
    assert dc.count == root.num_partitions
    assert all(k[0] == "hashagg_chain_update" for k in dc.keys)
    assert root.metrics.metric(M.STAGE_DISPATCHES).value == \
        root.num_partitions


def test_fused_stage_one_dispatch_per_batch(session):
    df = _chain_df(session)
    root, _ = convert_plan(df.plan, session.conf)
    assert isinstance(root, FusedStageExec)
    with _DispatchCounter() as dc:
        rows = _drain(root, ["k", "m", "dd"])
    assert rows
    assert dc.count == root.num_partitions  # one batch per partition
    assert all(k[0] == "fused_stage" for k in dc.keys)
    assert root.metrics.metric(M.STAGE_DISPATCHES).value == \
        root.num_partitions
    # per-member attribution: filter rows >= project rows == stage output
    fil, prj = root.members
    assert prj.metrics.metric(M.NUM_OUTPUT_ROWS).value == len(rows)
    assert fil.metrics.metric(M.NUM_OUTPUT_ROWS).value == len(rows)


def test_unfused_chain_pays_one_dispatch_per_op():
    s = TpuSession({"spark.rapids.sql.stageFusion.enabled": "false"})
    df = _chain_df(s)
    root, _ = convert_plan(df.plan, s.conf)
    with _DispatchCounter() as dc:
        _drain(root, ["k", "m", "dd"])
    assert dc.count == 2 * root.num_partitions  # filter + project per batch


# ---------------------------------------------------------------------------
# Result parity fused vs unfused
# ---------------------------------------------------------------------------

def _run_query(build, conf):
    s = TpuSession(conf)
    return build(s).collect().to_pylist()


# Tier-1 keeps the richest corner (masked input + ANSI both on); the other
# three combos of each parity grid run under the full @slow/CI pass.
_PARITY_MASKED = [pytest.param(False, marks=pytest.mark.slow), True]
_PARITY_ANSI = [pytest.param("false", marks=pytest.mark.slow), "true"]


@pytest.mark.parametrize("masked", _PARITY_MASKED)
@pytest.mark.parametrize("ansi", _PARITY_ANSI)
def test_chain_parity_fused_vs_unfused(ansi, masked):
    res = {}
    for flag in ("true", "false"):
        res[flag] = _run_query(
            lambda s: _chain_df(s, masked=masked),
            {"spark.rapids.sql.stageFusion.enabled": flag,
             "spark.sql.ansi.enabled": ansi})
    assert _eq(res["true"], res["false"])


@pytest.mark.parametrize("masked", _PARITY_MASKED)
@pytest.mark.parametrize("ansi", _PARITY_ANSI)
def test_agg_chain_parity_fused_vs_unfused(ansi, masked):
    def build(s):
        df = gen_df(s, _SPEC, length=2200, seed=23, num_partitions=3)
        if masked:
            df = df.filter(col("s").is_not_null())
        return (df.filter(col("v").is_not_null())
                .select((col("d") * lit(1.5)).alias("g"),
                        (col("v") % lit(97)).alias("m"))
                .group_by(col("g")).agg(F.sum("m").alias("sm"),
                                        F.count().alias("n")))

    res = {}
    for flag in ("true", "false"):
        got = _run_query(build, {
            "spark.rapids.sql.stageFusion.enabled": flag,
            "spark.sql.ansi.enabled": ansi})
        res[flag] = sorted(
            got, key=lambda r: (r["g"] is None,
                                r["g"] if r["g"] is not None
                                and not math.isnan(r["g"]) else 1e308))
    assert _eq(res["true"], res["false"])


def test_empty_batches_parity():
    res = {}
    for flag in ("true", "false"):
        res[flag] = _run_query(
            lambda s: _chain_df(s).filter(col("m") > lit(10 ** 9)),
            {"spark.rapids.sql.stageFusion.enabled": flag})
    assert res["true"] == res["false"] == []


def test_empty_source_parity():
    t = pa.table({"k": pa.array([], pa.int64()),
                  "v": pa.array([], pa.int64())})
    res = {}
    for flag in ("true", "false"):
        s = TpuSession({"spark.rapids.sql.stageFusion.enabled": flag})
        res[flag] = (s.create_dataframe(t)
                     .filter(col("v") > lit(0))
                     .select((col("k") + lit(1)).alias("k1"),
                             (col("v") * lit(2)).alias("v2"))
                     .collect().to_pylist())
    assert res["true"] == res["false"] == []


def test_ansi_error_still_raises_through_fused_stage():
    s = TpuSession({"spark.sql.ansi.enabled": "true"})
    df = (s.create_dataframe({"a": [1, 2, 3], "b": [1, 0, 2]})
          .filter(col("a") > lit(0))
          .select((col("a") / col("b")).alias("q"),
                  (col("a") + lit(1)).alias("a1"))
          .filter(col("a1") > lit(0)))
    root, _ = convert_plan(df.plan, s.conf)
    assert isinstance(root, FusedStageExec)
    with pytest.raises(SparkException):
        df.collect()


def test_row_base_carry_threads_through_fused_stage():
    """monotonically_increasing_id needs the row_base carry: fused and
    unfused chains must assign the same ids across batches."""
    res = {}
    for flag in ("true", "false"):
        s = TpuSession({"spark.rapids.sql.stageFusion.enabled": flag,
                        "spark.rapids.sql.reader.batchSizeRows": "256"})
        df = (s.create_dataframe(
            {"v": list(range(2000))}, num_partitions=2)
            .filter(col("v") % lit(3) > lit(0))
            .select(col("v"), F.monotonically_increasing_id().alias("id"))
            .filter(col("v") > lit(10)))
        res[flag] = sorted(df.collect().to_pylist(),
                           key=lambda r: r["v"])
    assert res["true"] == res["false"]


def test_limit_in_fused_chain_parity():
    for flag in ("true", "false"):
        s = TpuSession({"spark.rapids.sql.stageFusion.enabled": flag})
        df = (s.create_dataframe({"v": list(range(100))})
              .filter(col("v") > lit(4))
              .limit(20)
              .select((col("v") * lit(2)).alias("w"))
              .filter(col("w") < lit(40)))
        got = df.collect().to_pydict()["w"]
        assert got == [v * 2 for v in range(5, 20)]


def test_limit_fused_stage_stops_consuming_input():
    """A small LIMIT in a fused chain must still early-exit: once the
    device budget carry hits zero the driver stops pulling batches."""
    s = TpuSession({"spark.rapids.sql.reader.batchSizeRows": "128"})
    df = (s.create_dataframe({"v": list(range(4000))})
          .filter(col("v") >= lit(0))
          .limit(50)
          .select((col("v") + lit(1)).alias("w"))
          .filter(col("w") > lit(0)))
    root, _ = convert_plan(df.plan, s.conf)
    assert isinstance(root, FusedStageExec)
    with _DispatchCounter() as dc:
        rows = _drain(root, ["w"])
    assert [r["w"] for r in rows] == list(range(1, 51))
    # 4000 rows / 128 per batch = 32 batches; the stage must stop after
    # the first (limit-filling) batch, not drain the input
    assert dc.count <= 2


def test_expand_grouping_sets_parity():
    """ROLLUP lowers to Expand under an aggregate — the expand body path."""
    res = {}
    for flag in ("true", "false"):
        s = TpuSession({"spark.rapids.sql.stageFusion.enabled": flag})
        df = gen_df(s, [("a", RepeatSeqGen(IntegerGen(min_val=0, max_val=4),
                                           length=7)),
                        ("b", RepeatSeqGen(IntegerGen(min_val=0, max_val=3),
                                           length=5)),
                        ("v", LongGen(min_val=0, max_val=1000))],
                    length=600, seed=41, num_partitions=2)
        got = (df.rollup(col("a"), col("b"))
               .agg(F.sum("v").alias("sv"), F.count().alias("n"))
               .collect().to_pylist())
        res[flag] = sorted(
            got, key=lambda r: (r["a"] is None, r["a"] or 0,
                                r["b"] is None, r["b"] or 0))
    assert _eq(res["true"], res["false"])


def test_expand_absorbed_into_agg_one_dispatch_per_batch(session):
    """ROLLUP over a float key: the Expand body fuses into the (general-
    path) aggregate update — one dispatch per input batch."""
    rng = np.random.default_rng(13)
    n = 2000
    t = pa.table({"g": rng.uniform(0, 5, n).round(0),
                  "v": rng.integers(0, 100, n)})
    df = (session.create_dataframe(t, num_partitions=2)
          .rollup(col("g")).agg(F.sum("v").alias("sv"),
                                F.count().alias("n")))
    node = df.plan
    while not isinstance(node, P.Aggregate):
        node = node.children[0]
    child, _ = convert_plan(node.children[0], session.conf)
    assert isinstance(child, X.ExpandExec)
    agg = X.HashAggregateExec(node, [child], session.conf, mode="partial")
    root = fuse_stages(agg, session.conf)
    assert "ExpandExec" in [type(m).__name__
                            for m in root.pre_chain_members]
    with _DispatchCounter() as dc:
        rows = _drain(root, [f.name for f in root.state_fields()])
    assert dc.count == root.num_partitions
    # every live input row appears once per rollup projection
    total = sum(1 for _ in rows)
    assert total >= 2  # grouped states, not raw rows
    # parity against the full unfused query
    res = {}
    for flag in ("true", "false"):
        s = TpuSession({"spark.rapids.sql.stageFusion.enabled": flag})
        got = (s.create_dataframe(t, num_partitions=2)
               .rollup(col("g")).agg(F.sum("v").alias("sv"),
                                     F.count().alias("n"))
               .collect().to_pylist())
        res[flag] = sorted(got, key=lambda r: (r["g"] is None, r["g"] or 0))
    assert _eq(res["true"], res["false"])


def test_expand_over_masked_input_parity():
    """Filter→Expand absorbed into the aggregate: live rows of the masked
    filter output sit past the live count, and the expand body must not
    null them (regression: validity defaulted to arange<num_rows)."""
    rng = np.random.default_rng(29)
    n = 1500
    t = pa.table({"g": rng.uniform(0, 5, n).round(0),
                  "b": rng.integers(0, 3, n),
                  "v": rng.integers(0, 100, n)})
    res = {}
    for flag in ("true", "false"):
        s = TpuSession({"spark.rapids.sql.stageFusion.enabled": flag})
        got = (s.create_dataframe(t, num_partitions=2)
               .filter(col("v") % lit(7) > lit(1))  # masked batches
               .rollup(col("g"), col("b"))
               .agg(F.sum("v").alias("sv"), F.count().alias("n"))
               .collect().to_pylist())
        res[flag] = sorted(
            got, key=lambda r: (r["g"] is None, r["g"] or 0,
                                r["b"] is None, r["b"] or 0))
    assert _eq(res["true"], res["false"])


def test_last_metrics_no_duplicate_subtrees(session):
    """Fused members are snapshotted once, without re-walking the shared
    chain/input subtrees through their stale children links."""
    df = _chain_df(session)
    df.session = session
    session.collect(df.plan)
    keys = list(session.last_metrics().keys())
    scans = [k for k in keys if k.startswith("InMemoryScanExec")]
    assert len(scans) <= 1
    fused = [k for k in keys if k.startswith("FusedStageExec")]
    assert len(fused) == 1


def test_absorbed_chain_trace_failure_falls_back(session, monkeypatch):
    root, df = _partial_agg_chain(session)
    assert root.pre_chain is not None
    root._chain_key = lambda ansi: ("hashagg_chain_broken_test", ansi)

    def broken(ansi):
        def build():
            def fn(batch, pid):
                raise RuntimeError("synthetic trace failure")
            return fn
        return build

    monkeypatch.setattr(root, "_build_chain_update", broken)
    rows = _drain(root, [f.name for f in root.state_fields()])
    assert root._chain_failed
    assert rows  # unfused chain + plain update produced the partials
    # a fresh, unbroken exec over the same plan agrees
    ref_root, _ = _partial_agg_chain(TpuSession(
        {"spark.rapids.sql.stageFusion.enabled": "false"}))
    want = _drain(ref_root, [f.name for f in ref_root.state_fields()])
    key = ref_root.plan.group_names[0]
    srt = lambda rs: sorted(  # noqa: E731
        rs, key=lambda r: (r[key] is None, r[key] or 0))
    assert _eq(srt(rows), srt(want))


def test_trace_failure_falls_back_to_unfused(session, monkeypatch):
    df = _chain_df(session)
    root, _ = convert_plan(df.plan, session.conf)
    assert isinstance(root, FusedStageExec)
    root._key = ("fused_stage_broken_test", root._key)

    def broken_build():
        def fn(batch, pid, carries):
            raise RuntimeError("synthetic trace failure")
        return fn

    monkeypatch.setattr(root, "_build", lambda: broken_build)
    got = _drain(root, ["k", "m", "dd"])
    assert root._failed
    s2 = TpuSession({"spark.rapids.sql.stageFusion.enabled": "false"})
    want = _run_query(lambda s: _chain_df(s2),
                      {"spark.rapids.sql.stageFusion.enabled": "false"})
    assert _eq(got, want)


def test_differential_group_by_under_fusion():
    for flag in ("true", "false"):
        s = TpuSession({"spark.rapids.sql.stageFusion.enabled": flag})
        assert_tpu_and_cpu_are_equal_collect(
            lambda ss: gen_df(ss, _SPEC, length=1500, seed=67,
                              num_partitions=3)
            .filter(col("v").is_not_null())
            .select(col("k"), (col("v") % lit(50)).alias("m"))
            .group_by(col("k")).agg(F.sum("m").alias("sm"),
                                    F.count().alias("n")),
            s, ignore_order=True)


# ---------------------------------------------------------------------------
# Satellites: host pool + coalesce metrics
# ---------------------------------------------------------------------------

def test_host_pool_is_process_wide_and_bounded():
    from spark_rapids_tpu.runtime.host_pool import (
        get_host_pool, reset_host_pool,
    )
    reset_host_pool()
    try:
        s = TpuSession()
        pool = get_host_pool(s.conf)
        assert pool is get_host_pool()  # one shared instance
        assert pool.n_threads == s.conf.get(C.MULTIFILE_READER_THREADS)
        assert list(pool.map_ordered(lambda x: x * x, range(8))) == \
            [x * x for x in range(8)]

        def nested(x):
            # a worker submitting to its own pool must not deadlock
            return sum(pool.map_ordered(lambda y: y + x, range(4)))

        assert list(pool.map_ordered(nested, range(32))) == \
            [sum(y + x for y in range(4)) for x in range(32)]
    finally:
        reset_host_pool()


def test_prefetched_uses_host_pool(tmp_path):
    """Parquet scans prefetch on the shared pool — no throwaway executors
    (thread names carry the pool prefix)."""
    import threading
    from spark_rapids_tpu.runtime.host_pool import reset_host_pool
    reset_host_pool()
    try:
        import pyarrow.parquet as pq
        path = str(tmp_path / "t.parquet")
        pq.write_table(pa.table({"a": list(range(5000))}), path,
                       row_group_size=500)
        s = TpuSession()
        got = s.read_parquet(path).collect()
        assert got.num_rows == 5000
        names = {t.name for t in threading.enumerate()}
        assert any(n.startswith("rapids-host-pool") for n in names)
    finally:
        reset_host_pool()


def test_exchange_uses_host_pool_and_matches():
    from spark_rapids_tpu.plan.nodes import bind_expr
    s = TpuSession()
    df = gen_df(s, _SPEC, length=1200, seed=31, num_partitions=4)
    child, _ = convert_plan(df.plan, s.conf)
    ex = X.ShuffleExchangeExec(df.plan, [child], s.conf,
                               [bind_expr(col("k"), df.plan.schema)],
                               n_out=4)
    parts = [_drain_one(ex, p, list(df.plan.schema.names))
             for p in range(4)]
    assert sum(len(p) for p in parts) == 1200


def _drain_one(ex, p, names):
    rows = []
    with TaskContext(partition_id=p) as ctx:
        for b in ex.execute_partition(ctx, p):
            rows.extend(to_arrow(b, names).to_pylist())
    return rows


def test_coalesce_batches_counts_outputs(session):
    df = gen_df(session, _SPEC, length=900, seed=7, num_partitions=1)
    child, _ = convert_plan(df.plan, session.conf)
    co = X.CoalesceBatchesExec(df.plan, [child], session.conf)
    n_out = 0
    with TaskContext(partition_id=0) as ctx:
        for _ in co.execute_partition(ctx, 0):
            n_out += 1
    assert co.metrics.metric(M.NUM_OUTPUT_BATCHES).value == n_out
    assert co.metrics.metric(M.NUM_INPUT_BATCHES).value >= n_out


def test_coalesce_single_batch_skips_semaphore(session):
    """len(pending) == 1 short-circuits: no concat kernel, no semaphore
    acquire, and the metrics still record the passthrough output."""
    df = gen_df(session, _SPEC, length=100, seed=9, num_partitions=1)
    child, _ = convert_plan(df.plan, session.conf)
    co = X.CoalesceBatchesExec(df.plan, [child], session.conf)
    acquired = []
    co._acquire = lambda ctx: acquired.append(1)
    with TaskContext(partition_id=0) as ctx:
        out = list(co.execute_partition(ctx, 0))
    assert len(out) == 1
    assert not acquired
    assert co.metrics.metric(M.NUM_OUTPUT_BATCHES).value == 1
    assert co.metrics.metric(M.CONCAT_TIME).value == 0
