"""Flight recorder, time attribution & SLO detection regression.

Covers the round-10 acceptance bars: ring-buffer bounds, a dump fired
by EACH trigger class (query failure, degradation, watchdog timeout,
breaker open, SLO breach) with clean runs silent, dumps that validate
as Chrome-trace JSON with tracing OFF, attribution bucket sums
reconciling with query wall time (<1%, the PR 3 reconciliation bar),
and the disabled/always-on fast paths staying cheap (the hard 2% gate
lives in tools/flight_smoke.py with the trace-overhead counting
methodology — wall-clock gates here would flake on shared CI)."""
import glob
import importlib.util
import json
import os
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.runtime import obs, trace, watchdog
from spark_rapids_tpu.runtime.metrics import GpuMetric
from spark_rapids_tpu.runtime.obs import attribution, flight
from spark_rapids_tpu.runtime.obs.slo import SloDetector
from spark_rapids_tpu.sql.session import TpuSession

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_spec = importlib.util.spec_from_file_location(
    "profiler_report", os.path.join(REPO, "tools", "profiler_report.py"))
PR = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(PR)

from spark_rapids_tpu.expr.core import col, lit  # noqa: E402
from spark_rapids_tpu.sql import functions as F  # noqa: E402


def _table(n=20_000):
    rng = np.random.default_rng(7)
    return pa.table({"k": rng.integers(0, 20, n),
                     "v": rng.integers(0, 100, n)})


def _sess(tmp_path, **over):
    conf = {"spark.rapids.obs.flight.path": str(tmp_path / "flight"),
            "spark.rapids.obs.flight.minIntervalSeconds": "0",
            "spark.rapids.sql.reader.batchSizeRows": "4096"}
    conf.update(over)
    return TpuSession(conf)


def _query(sess, parts=2):
    return (sess.create_dataframe(_table(), num_partitions=parts)
            .filter(col("v") > lit(10))
            .group_by("k").agg(F.sum(col("v")).alias("sv")))


def _dumps(tmp_path):
    return sorted(glob.glob(str(tmp_path / "flight" / "flight_*.json")))


# ---------------------------------------------------------------------------
# ring buffer mechanics
# ---------------------------------------------------------------------------

def test_ring_is_bounded_and_keeps_newest(tmp_path):
    rec = flight.FlightRecorder(capacity=16, out_dir=str(tmp_path),
                                min_interval_s=0.0)
    for i in range(100):
        rec.record(f"e{i}", "t", i, 1)
    path = rec.dump("test")
    doc = json.load(open(path))
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 16
    # the NEWEST 16 events survive, the oldest 84 were overwritten
    assert {e["name"] for e in spans} == {f"e{i}" for i in range(84, 100)}
    assert doc["otherData"]["dropped_events"] == 84


def test_flight_span_feeds_metric_and_ring(tmp_path):
    rec = flight.FlightRecorder(capacity=64, out_dir=str(tmp_path),
                                min_interval_s=0.0)
    m = GpuMetric("opTime")
    with rec.span("Exec.opTime", m, "exec"):
        time.sleep(0.002)
    assert m.value >= 2_000_000  # the paired GpuMetric still times
    events = PR.validate_chrome_trace(rec.dump("test"))
    spans = [e for e in events if e["name"] == "Exec.opTime"]
    assert len(spans) == 1 and spans[0]["dur"] >= 2000  # us


def test_instants_and_rate_limit(tmp_path):
    rec = flight.FlightRecorder(capacity=64, out_dir=str(tmp_path),
                                min_interval_s=60.0)
    rec.instant("somethingHappened", "t", {"x": 1})
    p1 = rec.dump("first")
    assert p1 is not None
    assert rec.dump("second") is None  # rate-limited
    events = PR.validate_chrome_trace(p1)
    inst = [e for e in events if e["name"] == "somethingHappened"]
    assert len(inst) == 1 and inst[0]["ph"] == "i" \
        and inst[0]["args"] == {"x": 1}


def test_dump_retention_bounded(tmp_path):
    rec = flight.FlightRecorder(capacity=16, out_dir=str(tmp_path),
                                min_interval_s=0.0, max_dumps=3)
    rec.record("e", "t", 0, 1)
    for _ in range(5):
        rec.dump("test")
    files = sorted(glob.glob(str(tmp_path / "flight_*.json")))
    assert len(files) == 3
    assert files[-1].endswith("flight_0005_test.json")


def test_dump_retention_survives_seq_past_9999(tmp_path):
    # lexicographic pruning would sort flight_10001 before flight_9999
    # and delete the NEWEST dumps; pruning must parse the seq
    rec = flight.FlightRecorder(capacity=16, out_dir=str(tmp_path),
                                min_interval_s=0.0, max_dumps=3)
    rec.record("e", "t", 0, 1)
    rec._seq = 9998
    for _ in range(4):
        rec.dump("test")
    kept = sorted(os.path.basename(p)
                  for p in glob.glob(str(tmp_path / "flight_*.json")))
    assert set(kept) == {"flight_10000_test.json",
                         "flight_10001_test.json",
                         "flight_10002_test.json"}, kept


def test_failed_write_does_not_eat_the_rate_interval(tmp_path):
    # out_dir collides with a regular FILE: makedirs raises, nothing is
    # written (chmod tricks don't work under root, a path collision does)
    blocked = tmp_path / "blocked"
    blocked.write_text("in the way")
    rec = flight.install(capacity=16, out_dir=str(blocked),
                         min_interval_s=3600.0)
    rec.record("e", "t", 0, 1)
    assert flight.dump("first") is None  # write failed, swallowed
    # the failed attempt must not have armed the rate limiter: the next
    # trigger (disk freed / path fixed) still dumps within the interval
    rec.out_dir = str(tmp_path / "ok")
    assert flight.dump("second") is not None


def test_trace_fastpaths_feed_flight_when_tracing_off(tmp_path):
    assert trace.active() is None
    rec = flight.install(capacity=64, out_dir=str(tmp_path))
    m = GpuMetric("opTime")

    class _Node:
        lore_id = None

        def name(self):
            return "FakeExec"

    with trace.exec_span(_Node(), m):
        pass
    with trace.metric_span("manual.span", m):
        pass
    with trace.span("plain.span"):
        pass
    trace.instant("anInstant")
    # DEBUG-level events must NOT reach the bounded ring
    with trace.span("debug.span", level=trace.DEBUG):
        pass
    trace.instant("debugInstant", level=trace.DEBUG)
    names = {e["name"]
             for e in PR.validate_chrome_trace(rec.dump("test"))}
    assert {"FakeExec.opTime", "manual.span", "plain.span",
            "anInstant"} <= names
    assert "debug.span" not in names and "debugInstant" not in names


def test_traced_debug_spans_filtered_from_ring(tmp_path):
    # with a DEBUG-level tracer active, _Span also feeds the ring — but
    # DEBUG spans must still be filtered or serde chatter flushes it
    from spark_rapids_tpu import config as C
    rec = flight.install(capacity=64, out_dir=str(tmp_path))
    qt = trace.start_query(C.RapidsConf({
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.path": str(tmp_path / "tr"),
        "spark.rapids.sql.trace.level": "DEBUG"}))
    try:
        with trace.span("moderate.span"):
            pass
        with trace.span("debug.span", level=trace.DEBUG):
            pass
    finally:
        trace.end_query(qt)
    names = {e["name"]
             for e in PR.validate_chrome_trace(rec.dump("test"))}
    assert "moderate.span" in names
    assert "debug.span" not in names


def test_disabled_path_returns_pretrace_objects():
    flight.uninstall_for_tests()
    m = GpuMetric("opTime")
    span = trace.metric_span("x", m)
    # recorder off + tracer off = the bare metric timer, exactly as
    # before the flight recorder existed
    assert type(span).__name__ == "_Timer"
    assert trace.span("x") is trace._NULL
    assert flight.dump("nothing") is None
    assert flight.doc() is None


# ---------------------------------------------------------------------------
# trigger classes (tracing OFF throughout)
# ---------------------------------------------------------------------------

def test_failed_query_dumps_readable_trace(tmp_path):
    sess = _sess(tmp_path,
                 **{"spark.rapids.debug.faults": "scan.decode:ioerror"})
    with pytest.raises(Exception):
        _query(sess).collect()
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1 and "query_failed" in dumps[0]
    events = PR.validate_chrome_trace(dumps[0])
    names = {e["name"] for e in events}
    # the dump covers the failing query: exec spans + the fault + the
    # outcome marker + the trigger
    assert sum(1 for e in events if e["ph"] == "X") > 0
    assert "faultInjected" in names
    assert "queryError" in names
    assert "flightTrigger" in names
    other = json.load(open(dumps[0]))["otherData"]
    assert other["reason"] == "query_failed"
    assert other["error"] == "InjectedFaultError"


def test_clean_queries_stay_silent(tmp_path):
    sess = _sess(tmp_path)
    for _ in range(3):
        _query(sess).collect()
    assert _dumps(tmp_path) == []


def test_degraded_query_dumps(tmp_path):
    clean = _query(_sess(tmp_path)).collect()
    assert _dumps(tmp_path) == []
    sess = _sess(tmp_path, **{
        "spark.rapids.debug.faults": "scan.decode:ioerror",
        "spark.rapids.fallback.cpu.enabled": "true"})
    out = _query(sess).collect()
    assert sess.last_action_status[0] == "degraded"
    assert out.sort_by("k").equals(clean.sort_by("k"))
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1 and "query_degraded" in dumps[0]
    other = json.load(open(dumps[0]))["otherData"]
    assert other["reason"] == "query_degraded"


def test_watchdog_timeout_dumps(tmp_path):
    flight.install(capacity=64, out_dir=str(tmp_path / "flight"))
    wd = watchdog.DispatchWatchdog(timeout_s=0.03)
    wd.start()
    try:
        with wd.guard("device.dispatch"):
            time.sleep(0.3)  # the "wedge": guard held past the deadline
        deadline = time.time() + 5
        while wd.timeouts_reported == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert wd.timeouts_reported >= 1
    finally:
        wd.stop()
        watchdog.uninstall_for_tests()
    dumps = _dumps(tmp_path)
    assert dumps and "watchdog_timeout" in dumps[0]
    events = PR.validate_chrome_trace(dumps[0])
    assert any(e["name"] == "watchdogDispatchTimeout" for e in events)


def test_breaker_open_dumps(tmp_path):
    flight.install(capacity=64, out_dir=str(tmp_path / "flight"))
    brk = watchdog.CircuitBreaker(failure_threshold=1)
    brk.record_failure("SomeDeviceError")
    assert brk.state == "open"
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1 and "breaker_open" in dumps[0]
    other = json.load(open(dumps[0]))["otherData"]
    assert other["error"] == "SomeDeviceError"


# ---------------------------------------------------------------------------
# SLO detection
# ---------------------------------------------------------------------------

def test_slo_baseline_detector_unit():
    det = SloDetector(factor=2.0, min_runs=3, abs_seconds=0.0)
    assert det.record("d1", 1.0) is None
    assert det.record("d1", 1.1) is None
    # under min_runs: even a huge outlier folds silently
    assert det.record("d1", 0.9) is None
    assert det.record("d1", 1.9) is None  # under 2x baseline
    b = det.record("d1", 5.0)
    assert b is not None and b["kind"] == "baseline"
    assert 0.9 < b["baseline_seconds"] < 1.5 and b["runs"] >= 3
    # the breaching run did NOT fold in: a repeat still breaches
    b2 = det.record("d1", 5.0)
    assert b2 is not None and abs(
        b2["baseline_seconds"] - b["baseline_seconds"]) < 1e-9
    assert det.breaches == 2


def test_slo_absolute_bound_and_window():
    det = SloDetector(factor=100.0, min_runs=2, abs_seconds=0.5, window=4)
    assert det.record("d", 0.4) is None
    b = det.record("d", 0.6)
    assert b is not None and b["kind"] == "absolute" \
        and b["threshold_seconds"] == 0.5
    for i in range(10):
        det.observe("d", float(i))
    assert det.baseline("d")["runs"] == 4  # window bounds the history


def test_slo_disabled_never_breaches():
    det = SloDetector(enabled=False, abs_seconds=0.001)
    assert det.record("d", 10.0) is None
    assert det.breaches == 0


def test_slo_seed_skips_breaching_runs():
    # a breaching run is status=ok in history but carries slo_breach:
    # folding it at seed time would normalize the regression away
    # across restarts — the live-check invariant applies to seeding too
    class _Store:
        def read_all(self):
            return ([{"type": "query", "status": "ok", "plan_digest": "d",
                      "duration_ns": 1_000_000_000}] * 3
                    + [{"type": "query", "status": "ok",
                        "plan_digest": "d", "duration_ns": 60_000_000_000,
                        "slo_breach": {"kind": "baseline"}}])

    det = SloDetector(factor=3.0, min_runs=3)
    assert det.seed_from_history(_Store()) == 3
    base = det.baseline("d")
    assert base["runs"] == 3 and base["mean_seconds"] < 1.5
    assert det.record("d", 5.0) is not None  # still reads as a breach


def test_slo_breach_end_to_end(tmp_path):
    obs.shutdown_for_tests()
    try:
        hist = tmp_path / "hist"
        sess = _sess(tmp_path, **{
            "spark.rapids.obs.historyDir": str(hist),
            "spark.rapids.obs.slo.latencySeconds": "0.000001"})
        _query(sess).collect()
        st = obs.state()
        assert st.slo.breaches == 1
        # counter, healthz surface, flight dump, history record
        assert st.registry.counter("rapids_slo_breaches_total").value == 1
        hz = obs.healthz()
        last_slow = hz["slo"]["last_slow"]
        assert last_slow["plan_digest"]
        assert last_slow["breach"]["kind"] == "absolute"
        assert last_slow["attribution"]["top_buckets"]
        assert last_slow["flight_dump"] and os.path.exists(
            last_slow["flight_dump"])
        assert hz["flight"]["last_dump"]["reason"] == "slo_breach"
        events = PR.validate_chrome_trace(last_slow["flight_dump"])
        assert any(e["name"] == "slowQuery" for e in events)
        recs = [r for r in st.history.read_all()
                if r.get("type") == "query"]
        assert recs[-1]["slo_breach"]["kind"] == "absolute"
        assert recs[-1]["flight_dump"] == last_slow["flight_dump"]
        assert recs[-1]["attribution"]["buckets"]
        # /metrics exports the per-phase seconds counters
        rendered = st.registry.render_prometheus()
        assert 'rapids_query_seconds_bucket{phase="device_compute"}' \
            in rendered
    finally:
        obs.shutdown_for_tests()


def test_slo_baselines_seed_from_history(tmp_path):
    obs.shutdown_for_tests()
    try:
        hist = tmp_path / "hist"
        sess = _sess(tmp_path,
                     **{"spark.rapids.obs.historyDir": str(hist)})
        for _ in range(3):
            _query(sess).collect()
        obs.shutdown_for_tests()
        # a fresh "process": the detector seeds from the store
        sess2 = _sess(tmp_path, **{
            "spark.rapids.obs.historyDir": str(hist),
            "spark.rapids.obs.slo.minRuns": "3"})
        st = obs.state()
        digest = obs.plan_digest(_query(sess2).plan)
        base = st.slo.baseline(digest)
        assert base is not None and base["runs"] >= 3
    finally:
        obs.shutdown_for_tests()


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

def test_attribution_reconciles_with_wall_time(tmp_path):
    sess = _sess(tmp_path)
    t0 = time.perf_counter()
    _query(sess).collect()
    wall_outer = time.perf_counter() - t0
    attr = sess.last_attribution()
    assert attr is not None
    assert set(attr["buckets"]) == set(attribution.BUCKETS)
    total = sum(attr["buckets"].values())
    # the acceptance bar: buckets sum to wall within 1%
    assert abs(total - attr["wall_seconds"]) <= 0.01 * attr["wall_seconds"]
    # the measured wall is the engine's own timing of the same action
    assert attr["wall_seconds"] <= wall_outer * 1.05
    assert all(v >= 0 for v in attr["buckets"].values())
    assert attr["buckets"]["device_compute"] + attr["buckets"]["compile"] > 0


def test_attribution_compile_bucket_on_fresh_cache(tmp_path):
    from spark_rapids_tpu.exec import fuse
    fuse.clear_cache()
    sess = _sess(tmp_path)
    _query(sess).collect()
    attr = sess.last_attribution()
    # a cold fuse cache means the first dispatches paid XLA compile
    assert attr["buckets"]["compile"] > 0


def test_attribution_in_explain_analyze(tmp_path, capsys):
    sess = _sess(tmp_path)
    df = _query(sess)
    text = df.explain(mode="analyze")
    capsys.readouterr()
    assert "-- time attribution (wall " in text
    # at least one named bucket line renders with seconds and percent
    assert any(b in text for b in ("device_compute", "compile"))
    assert "%" in text


def test_attribution_concurrency_scaling():
    # measured > wall: buckets scale to critical-path shares
    snaps = {"FakeExec#0": {"opTime": 4_000_000_000}}
    doc = attribution.attribute(snaps, 1_000_000_000)
    assert doc["concurrency_factor"] == pytest.approx(4.0)
    assert doc["buckets"]["device_compute"] == pytest.approx(1.0)
    assert sum(doc["buckets"].values()) == pytest.approx(
        doc["wall_seconds"])
    # measured < wall: the remainder is 'other'
    doc2 = attribution.attribute(snaps, 8_000_000_000)
    assert doc2["concurrency_factor"] == 1.0
    assert doc2["buckets"]["other"] == pytest.approx(4.0)


def test_attribution_classification_and_compile_correction():
    snaps = {
        "InMemoryScanExec#0": {"tpuDecodeTime": 10, "copyToDeviceTime": 10,
                               "numOutputRows": 99},
        "ShuffleExchangeExec#1": {"partitionTime": 30, "opTime": 10},
        "PipelineExec#2": {"pipelineStallTime": 25,
                           "pipelineProducerTime": 1000},  # excluded
        "FilterExec#3": {"filterTime": 40},
    }
    extra = {"compile": 15, "semaphore_wait": 5}
    doc = attribution.attribute(snaps, 1_000_000_000, extra=extra)
    ns = {b: round(s * 1e9) for b, s in doc["buckets"].items()}
    assert ns["host_decode"] == 20
    assert ns["shuffle"] == 40  # partitionTime + exchange opTime
    assert ns["pipeline_stall"] == 25
    assert ns["semaphore_wait"] == 5
    # compile correction: 15ns move OUT of device_compute (40 - 15)
    assert ns["compile"] == 15 and ns["device_compute"] == 25
    assert sum(ns.values()) == 1_000_000_000


def test_attribution_compile_correction_cascades_past_device():
    # a fresh EXCHANGE kernel's first call times into 'shuffle': the
    # compile subtraction must cascade there once device_compute is
    # exhausted, not leave the interval double-counted (which would
    # inflate measured_seconds and fake a concurrency factor)
    snaps = {"ShuffleExchangeExec#0": {"partitionTime": 100},
             "FilterExec#1": {"filterTime": 30}}
    doc = attribution.attribute(snaps, 1_000_000_000,
                                extra={"compile": 90})
    ns = {b: round(s * 1e9) for b, s in doc["buckets"].items()}
    assert ns["compile"] == 90
    assert ns["device_compute"] == 0   # 30 absorbed first
    assert ns["shuffle"] == 40         # then 60 of the 100
    assert doc["concurrency_factor"] == 1.0
    assert sum(ns.values()) == 1_000_000_000


def test_attribution_history_and_render(tmp_path):
    obs.shutdown_for_tests()
    try:
        hist = tmp_path / "hist"
        sess = _sess(tmp_path,
                     **{"spark.rapids.obs.historyDir": str(hist)})
        _query(sess).collect()
        st = obs.state()
        rec = [r for r in st.history.read_all()
               if r.get("type") == "query"][-1]
        attr = rec["attribution"]
        assert set(attr["buckets"]) == set(attribution.BUCKETS)
        # the text renderer emits one line per nonzero bucket
        lines = attribution.render_text(attr)
        assert lines and lines[0].startswith("-- time attribution")
        assert len(lines) - 1 == sum(
            1 for v in attr["buckets"].values() if v > 0)
    finally:
        obs.shutdown_for_tests()


def test_attribution_aggregate_cleared_between_queries(tmp_path):
    sess = _sess(tmp_path)
    _query(sess).collect()
    first = sess.last_attribution()
    # outside a query the aggregate must be closed (record is a no-op)
    attribution.record("compile", 10**12)
    _query(sess).collect()
    second = sess.last_attribution()
    assert second["buckets"]["compile"] <= first["buckets"]["compile"] + 1


# ---------------------------------------------------------------------------
# overhead guardrails (behavioral; the hard gate is flight_smoke.py)
# ---------------------------------------------------------------------------

def test_always_on_span_cost_is_bounded(tmp_path):
    rec = flight.install(capacity=2048, out_dir=str(tmp_path))
    m = GpuMetric("opTime")
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.metric_span("x", m):
            pass
    per_call_us = (time.perf_counter() - t0) / n * 1e6
    # generous CI-safe bound: the smoke gates the real 2% budget
    assert per_call_us < 50, f"flight span costs {per_call_us:.1f}us"
    assert rec.doc()["enabled"]


def test_dump_never_raises(tmp_path, monkeypatch):
    rec = flight.install(capacity=16, out_dir="/nonexistent\0bad")
    rec.record("e", "t", 0, 1)
    assert flight.dump("broken") is None  # swallowed + logged, not raised
