"""Python UDF worker pool tests (reference PySpark daemon analog)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql.udf import PythonRowUDF
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.runtime import pyworker


def _double(x):
    return None if x is None else x * 2


def test_pool_matches_inprocess():
    rows = [(i,) for i in range(20000)]
    got = pyworker.map_rows(_double, rows, parallelism=4)
    assert got is not None, "pool should accept a picklable module fn"
    assert got == [r[0] * 2 for r in rows]


def test_pool_declines_small_and_unpicklable():
    assert pyworker.map_rows(_double, [(1,)], parallelism=4) is None
    import threading
    lock = threading.Lock()  # unpicklable capture

    def bad(x):
        with lock:
            return x
    assert pyworker.map_rows(bad, [(i,) for i in range(20000)],
                             parallelism=4) is None


def test_udf_through_pool_end_to_end():
    s = TpuSession()
    n = 20000
    t = pa.table({"a": pa.array(np.arange(n, dtype=np.int64))})
    e = PythonRowUDF(_double, T.INT64, [col("a")])
    out = s.create_dataframe(t).select(e.alias("r")).to_pydict()["r"]
    assert out == [2 * i for i in range(n)]
    pyworker.shutdown_pool()
