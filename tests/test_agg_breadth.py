"""Differential tests for collect_list/set, min_by/max_by, percentile.

Reference parity: hash_aggregate_test.py collect/percentile coverage
(GpuCollectList/Set, GpuMinBy/MaxBy, GpuPercentile,
GpuApproximatePercentile).
"""
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect
from data_gen import (
    IntegerGen, LongGen, DoubleGen, StringGen, RepeatSeqGen, UniqueLongGen,
    gen_df,
)


@pytest.fixture
def session():
    return TpuSession()


DATA = {
    "k": pa.array(["a", "b", "a", None, "b", "a", None, "c"]),
    "v": pa.array([10, 20, None, 40, 50, 60, 70, None], pa.int64()),
    "o": pa.array([3, 1, 4, 1, 5, None, 2, 6], pa.int64()),
    "f": pa.array([1.5, 2.5, None, 4.5, 0.5, 3.5, 2.0, None]),
    "s": pa.array(["x", "y", "x", "z", None, "y", "w", "x"]),
}


def make_df(s, parts=1):
    return s.create_dataframe(dict(DATA), num_partitions=parts)


@pytest.mark.parametrize("parts", [1, 3])
def test_collect_list(session, parts):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, parts).group_by(col("k")).agg(
            F.collect_list(col("v")).alias("lv")),
        session, ignore_order=True)


@pytest.mark.parametrize("parts", [1, 3])
def test_collect_set(session, parts):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, parts).group_by(col("k")).agg(
            F.collect_set(col("v")).alias("sv"),
            F.collect_set(col("s")).alias("ss")),
        session, ignore_order=True, canonicalize_arrays=True)


def test_collect_list_strings(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).group_by(col("k")).agg(
            F.collect_list(col("s")).alias("ls")),
        session, ignore_order=True)


def test_collect_global(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).agg(F.collect_list(col("v")).alias("all"),
                                 F.collect_set(col("k")).alias("ks")),
        session, canonicalize_arrays=True)


def test_collect_empty_input(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).filter(col("v") > lit(10 ** 6))
        .agg(F.collect_list(col("v")).alias("e")),
        session)


@pytest.mark.parametrize("parts", [1, 3])
def test_min_by_max_by(session, parts):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, parts).group_by(col("k")).agg(
            F.min_by(col("v"), col("o")).alias("mnb"),
            F.max_by(col("v"), col("o")).alias("mxb"),
            F.min_by(col("s"), col("o")).alias("mnbs")),
        session, ignore_order=True)


def test_min_by_all_null_ord(session):
    t = {"k": pa.array(["a", "a", "b"]),
         "v": pa.array([1, 2, 3], pa.int64()),
         "o": pa.array([None, None, 5], pa.int64())}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).group_by(col("k")).agg(
            F.min_by(col("v"), col("o")).alias("m")),
        session, ignore_order=True)


@pytest.mark.parametrize("p", [0.0, 0.25, 0.5, 0.9, 1.0])
def test_percentile(session, p):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, 2).group_by(col("k")).agg(
            F.percentile(col("f"), p).alias("pf"),
            F.approx_percentile(col("v"), p).alias("pv")),
        session, ignore_order=True, approx_float=1e-12)


def test_agg_breadth_generated(session):
    spec = [("k", RepeatSeqGen(IntegerGen(min_val=0, max_val=25), length=20)),
            ("v", LongGen(min_val=-(1 << 40), max_val=1 << 40)),
            ("o", UniqueLongGen()),
            ("d", DoubleGen(min_val=-1e9, max_val=1e9))]
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=2048, seed=83, num_partitions=3)
        .group_by(col("k")).agg(
            F.collect_set(col("v")).alias("cs"),
            F.min_by(col("v"), col("o")).alias("mb"),
            F.max_by(col("d"), col("o")).alias("xb"),
            F.percentile(col("d"), 0.75).alias("p75"),
            F.sum("v").alias("sv")),
        session, ignore_order=True, approx_float=1e-9,
        canonicalize_arrays=True)


def test_collect_list_order_preserved_single_partition(session):
    # within one partition collect_list preserves input order (stable
    # group sort)
    out = make_df(session).group_by(col("k")).agg(
        F.collect_list(col("v")).alias("lv")).to_pydict()
    got = dict(zip(out["k"], out["lv"]))
    assert got["a"] == [10, 60]
    assert got["b"] == [20, 50]
    assert got[None] == [40, 70]
