"""Differential tests for higher-order functions (lambdas over arrays and
maps). Reference scope: sql-plugin higherOrderFunctions.scala."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


def _arrays(n=60, seed=7):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        if rng.random() < 0.1:
            rows.append(None)
            continue
        ln = int(rng.integers(0, 6))
        rows.append([None if rng.random() < 0.15 else int(v)
                     for v in rng.integers(-50, 50, ln)])
    base = rng.integers(1, 10, n).astype(np.int64)
    return pa.table({"a": pa.array(rows, pa.list_(pa.int64())),
                     "m": pa.array(base)})


def _two_arrays(n=50, seed=11):
    rng = np.random.default_rng(seed)

    def mk():
        rows = []
        for _ in range(n):
            if rng.random() < 0.1:
                rows.append(None)
                continue
            ln = int(rng.integers(0, 5))
            rows.append([int(v) for v in rng.integers(-20, 20, ln)])
        return pa.array(rows, pa.list_(pa.int64()))
    return pa.table({"a": mk(), "b": mk()})


def _maps(n=40, seed=3):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        if rng.random() < 0.1:
            rows.append(None)
            continue
        k = rng.choice(20, size=int(rng.integers(0, 5)), replace=False)
        rows.append([(int(kk), int(rng.integers(-30, 30))) for kk in k])
    return pa.table({"m": pa.array(rows, pa.map_(pa.int64(), pa.int64()))})


def test_transform_simple(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_arrays()).select(
            F.transform(col("a"), lambda x: x * lit(2) + lit(1)).alias("t")),
        session)


def test_transform_with_index_and_outer_ref(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_arrays()).select(
            F.transform(col("a"), lambda x, i: x + i).alias("ti"),
            F.transform(col("a"), lambda x: x * col("m")).alias("to")),
        session)


def test_filter_lambda(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_arrays()).select(
            F.filter(col("a"), lambda x: x > lit(0)).alias("f"),
            F.filter(col("a"), lambda x, i: i % lit(2) == lit(0)).alias("fe")),
        session)


def test_exists_forall_three_valued(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_arrays()).select(
            F.exists(col("a"), lambda x: x > lit(25)).alias("ex"),
            F.forall(col("a"), lambda x: x > lit(-49)).alias("fa")),
        session)


def test_zip_with(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_two_arrays()).select(
            F.zip_with(col("a"), col("b"),
                       lambda x, y: x + y).alias("z")),
        session)


def test_transform_values_and_map_filter(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_maps()).select(
            F.transform_values(col("m"), lambda k, v: v * lit(3)).alias("tv"),
            F.map_filter(col("m"), lambda k, v: v > lit(0)).alias("mf")),
        session)


def test_transform_keys(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_maps()).select(
            F.transform_keys(col("m"), lambda k, v: k + lit(100)).alias("tk")),
        session)


def test_aggregate_fold_cpu_tier(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_arrays()).select(
            F.aggregate(col("a"), lit(0),
                        lambda acc, x: acc + F.coalesce(x, lit(0))).alias("s"),
            F.aggregate(col("a"), lit(1),
                        lambda acc, x: acc * F.coalesce(x, lit(1)),
                        lambda acc: acc + lit(5)).alias("p")),
        session)


def test_nested_hof(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_arrays()).select(
            F.transform(F.filter(col("a"), lambda x: x.is_not_null()),
                        lambda x: x - lit(1)).alias("nf")),
        session)
