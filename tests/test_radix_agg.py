"""Differential tests for the packed-radix groupby backbone (ops/radix.py):
the round-3 performance path. Every case runs the same query through the
TPU engine (packed path when eligible) and the CPU backend / pyarrow and
compares exactly or within float tolerance."""
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit


def _sess():
    return TpuSession()


def _cmp(d, ref_rows, keys, cols, tol=1e-9):
    got = {tuple(d[k][i] for k in keys): tuple(d[c][i] for c in cols)
           for i in range(len(d[keys[0]]))}
    assert set(got) == set(ref_rows), (
        f"group sets differ: {len(got)} vs {len(ref_rows)}; "
        f"extra={list(set(got) - set(ref_rows))[:3]} "
        f"missing={list(set(ref_rows) - set(got))[:3]}")
    for k, want in ref_rows.items():
        have = got[k]
        for a, b in zip(have, want):
            if a is None or b is None:
                assert a is None and b is None, (k, have, want)
            elif isinstance(a, float) and (np.isnan(a) or np.isnan(b)):
                assert np.isnan(a) and np.isnan(b), (k, have, want)
            elif isinstance(a, float):
                assert abs(a - b) <= tol * max(1.0, abs(a), abs(b)), \
                    (k, have, want)
            else:
                assert a == b, (k, have, want)


def test_packed_int_key_sums_counts_minmax():
    rng = np.random.default_rng(1)
    n = 50_000
    t = pa.table({
        "k": rng.integers(-1000, 9000, n).astype(np.int64),
        "v": rng.uniform(-100, 100, n),
        "i": rng.integers(-10**6, 10**6, n).astype(np.int64),
    })
    g = (_sess().create_dataframe(t).group_by(col("k"))
         .agg(F.sum("v").alias("s"), F.count("v").alias("c"),
              F.min("v").alias("mnv"), F.max("v").alias("mxv"),
              F.min("i").alias("mni"), F.max("i").alias("mxi"),
              F.sum("i").alias("si")))
    d = g.to_pydict()
    ref = t.group_by(["k"]).aggregate([
        ("v", "sum"), ("v", "count"), ("v", "min"), ("v", "max"),
        ("i", "min"), ("i", "max"), ("i", "sum")])
    rows = {(k,): tuple(ref[c][i].as_py() for c in
                        ["v_sum", "v_count", "v_min", "v_max",
                         "i_min", "i_max", "i_sum"])
            for i, k in enumerate(ref["k"].to_pylist())}
    _cmp(d, rows, ["k"], ["s", "c", "mnv", "mxv", "mni", "mxi", "si"])


def test_packed_multi_key_with_nulls():
    rng = np.random.default_rng(2)
    n = 20_000
    k1 = rng.integers(0, 50, n).astype(np.int32)
    k2 = rng.integers(-5, 5, n).astype(np.int64)
    v = rng.uniform(0, 10, n)
    m1 = rng.random(n) < 0.1
    m2 = rng.random(n) < 0.2
    mv = rng.random(n) < 0.15
    t = pa.table({
        "a": pa.array(np.where(m1, None, k1), type=pa.int32()),
        "b": pa.array([None if m else int(x) for m, x in zip(m2, k2)],
                      type=pa.int64()),
        "v": pa.array([None if m else float(x) for m, x in zip(mv, v)]),
    })
    g = (_sess().create_dataframe(t).group_by(col("a"), col("b"))
         .agg(F.sum("v").alias("s"), F.count("v").alias("c"),
              F.avg("v").alias("m")))
    d = g.to_pydict()
    ref = t.group_by(["a", "b"]).aggregate([
        ("v", "sum"), ("v", "count"), ("v", "mean")])
    rows = {(a, b): (s, c, m) for a, b, s, c, m in zip(
        ref["a"].to_pylist(), ref["b"].to_pylist(), ref["v_sum"].to_pylist(),
        ref["v_count"].to_pylist(), ref["v_mean"].to_pylist())}
    _cmp(d, rows, ["a", "b"], ["s", "c", "m"])


def test_packed_merge_across_partitions():
    """Multiple input partitions force the state-merge path through the
    packed kernel too (partial -> exchange -> final merge)."""
    rng = np.random.default_rng(3)
    n = 40_000
    t = pa.table({
        "k": rng.integers(0, 3000, n).astype(np.int64),
        "v": rng.uniform(-1, 1, n),
    })
    df = _sess().create_dataframe(t, num_partitions=4)
    g = df.group_by(col("k")).agg(
        F.sum("v").alias("s"), F.count("v").alias("c"),
        F.max("v").alias("mx"))
    d = g.to_pydict()
    ref = t.group_by(["k"]).aggregate([("v", "sum"), ("v", "count"),
                                       ("v", "max")])
    rows = {(k,): (s, c, m) for k, s, c, m in zip(
        ref["k"].to_pylist(), ref["v_sum"].to_pylist(),
        ref["v_count"].to_pylist(), ref["v_max"].to_pylist())}
    _cmp(d, rows, ["k"], ["s", "c", "mx"])


def test_packed_float_specials_sum():
    """NaN / +-Inf propagate through the limb-sum with Spark semantics."""
    t = pa.table({
        "k": pa.array([1, 1, 2, 2, 3, 3, 4, 5, 5], type=pa.int64()),
        "v": pa.array([1.0, np.nan, np.inf, 2.0, np.inf, -np.inf,
                       -np.inf, 1.5, 2.5]),
    })
    g = (_sess().create_dataframe(t).group_by(col("k"))
         .agg(F.sum("v").alias("s")))
    d = g.to_pydict()
    got = dict(zip(d["k"], d["s"]))
    assert np.isnan(got[1])
    assert got[2] == np.inf
    assert np.isnan(got[3])  # inf + -inf
    assert got[4] == -np.inf
    assert abs(got[5] - 4.0) < 1e-12


def test_packed_sum_magnitude_spread():
    """Tiny values next to huge ones: limb decomposition error stays
    within 1 ulp of the batch max (comfortably inside 1e-9 relative for
    uniform-exponent groups, and bounded for mixed ones)."""
    rng = np.random.default_rng(4)
    n = 10_000
    k = rng.integers(0, 10, n).astype(np.int64)
    v = rng.uniform(1.0, 2.0, n) * (10.0 ** rng.integers(-3, 4, n))
    t = pa.table({"k": k, "v": v})
    g = (_sess().create_dataframe(t).group_by(col("k"))
         .agg(F.sum("v").alias("s")))
    d = g.to_pydict()
    ref = {}
    for kk in np.unique(k):
        ref[int(kk)] = float(np.sum(v[k == kk]))
    for kk, s in zip(d["k"], d["s"]):
        assert abs(s - ref[kk]) <= 1e-9 * max(1.0, abs(ref[kk])), (kk, s, ref[kk])


def test_packed_int64_sum_wraparound():
    """Long-sum overflow wraps mod 2^64 exactly like Java/Spark."""
    big = 2**62
    t = pa.table({"k": pa.array([1, 1, 1], type=pa.int64()),
                  "v": pa.array([big, big, big], type=pa.int64())})
    g = (_sess().create_dataframe(t).group_by(col("k"))
         .agg(F.sum("v").alias("s")))
    d = g.to_pydict()
    want = (3 * big) - 2**64  # wrapped
    assert d["s"][0] == want


def test_wide_span_falls_back():
    """Key span too wide to pack -> general path, still correct."""
    rng = np.random.default_rng(5)
    n = 5_000
    k = rng.integers(-2**62, 2**62, n).astype(np.int64)
    k[:100] = k[0]  # some duplicates
    t = pa.table({"k": k, "v": rng.uniform(0, 1, n)})
    g = (_sess().create_dataframe(t).group_by(col("k"))
         .agg(F.count("v").alias("c")))
    d = g.to_pydict()
    ref = t.group_by(["k"]).aggregate([("v", "count")])
    rows = {(kk,): (c,) for kk, c in zip(ref["k"].to_pylist(),
                                         ref["v_count"].to_pylist())}
    _cmp(d, rows, ["k"], ["c"])


def test_packed_bool_date_keys_first_last():
    rng = np.random.default_rng(6)
    n = 8_000
    import datetime
    days = rng.integers(18000, 18100, n)
    t = pa.table({
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "d": pa.array([datetime.date(1970, 1, 1)
                       + datetime.timedelta(days=int(x)) for x in days],
                      type=pa.date32()),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })
    g = (_sess().create_dataframe(t).group_by(col("b"), col("d"))
         .agg(F.first("v").alias("f"), F.last("v").alias("l"),
              F.sum("v").alias("s")))
    d = g.to_pydict()
    # reference first/last by original order
    import collections
    firsts, lasts, sums = {}, {}, collections.defaultdict(int)
    bs = t["b"].to_pylist()
    ds = t["d"].to_pylist()
    vs = t["v"].to_pylist()
    for b, dd, v in zip(bs, ds, vs):
        kk = (b, dd)
        if kk not in firsts:
            firsts[kk] = v
        lasts[kk] = v
        sums[kk] += v
    rows = {k: (firsts[k], lasts[k], sums[k]) for k in firsts}
    _cmp(d, rows, ["b", "d"], ["f", "l", "s"])


def test_packed_decimal_key_and_sum():
    import decimal
    t = pa.table({
        "k": pa.array([decimal.Decimal("1.10"), decimal.Decimal("1.10"),
                       decimal.Decimal("-2.25"), decimal.Decimal("-2.25"),
                       None],
                      type=pa.decimal128(9, 2)),
        "v": pa.array([1, 2, 3, 4, 5], type=pa.int64()),
    })
    g = (_sess().create_dataframe(t).group_by(col("k"))
         .agg(F.sum("v").alias("s")))
    d = g.to_pydict()
    got = {str(k) if k is not None else None: s
           for k, s in zip(d["k"], d["s"])}
    assert got == {"1.10": 3, "-2.25": 7, None: 5}


def test_packed_f32_and_small_int_minmax():
    rng = np.random.default_rng(7)
    n = 9_000
    t = pa.table({
        "k": rng.integers(0, 200, n).astype(np.int16),
        "f": rng.uniform(-5, 5, n).astype(np.float32),
        "s": rng.integers(-128, 127, n).astype(np.int8),
    })
    g = (_sess().create_dataframe(t).group_by(col("k"))
         .agg(F.min("f").alias("mnf"), F.max("f").alias("mxf"),
              F.min("s").alias("mns"), F.max("s").alias("mxs")))
    d = g.to_pydict()
    ref = t.group_by(["k"]).aggregate([("f", "min"), ("f", "max"),
                                       ("s", "min"), ("s", "max")])
    rows = {(k,): (a, b, c, e) for k, a, b, c, e in zip(
        ref["k"].to_pylist(), ref["f_min"].to_pylist(),
        ref["f_max"].to_pylist(), ref["s_min"].to_pylist(),
        ref["s_max"].to_pylist())}
    _cmp(d, rows, ["k"], ["mnf", "mxf", "mns", "mxs"], tol=1e-6)


def test_packed_timestamp_key():
    rng = np.random.default_rng(8)
    n = 5_000
    us = rng.integers(1_600_000_000_000_000, 1_600_000_500_000_000, n)
    t = pa.table({
        "ts": pa.array(us, type=pa.timestamp("us", tz="UTC")),
        "v": rng.integers(0, 10, n).astype(np.int64),
    })
    g = (_sess().create_dataframe(t).group_by(col("ts"))
         .agg(F.count("v").alias("c")))
    d = g.to_pydict()
    import collections
    cnt = collections.Counter(us.tolist())
    # span 5e8 us needs 30 bits -> still packs
    got_total = sum(d["c"])
    assert got_total == n
    assert len(d["ts"]) == len(cnt)


def test_single_device_agg_collapse(monkeypatch):
    """One device: partial+exchange+final collapses to one complete pass
    over the collected input (plan/overrides.py)."""
    import jax as _jax
    real = _jax.devices()
    monkeypatch.setattr(_jax, "devices", lambda *a, **k: real[:1])
    rng = np.random.default_rng(9)
    n = 30_000
    t = pa.table({"k": rng.integers(0, 500, n).astype(np.int64),
                  "v": rng.uniform(0, 1, n)})
    s = TpuSession()
    df = s.create_dataframe(t, num_partitions=4)
    g = df.group_by(col("k")).agg(F.sum("v").alias("s"),
                                  F.count("v").alias("c"))
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.exec import tpu_nodes as X
    root, _ = convert_plan(g.plan, s.conf)
    kinds = []
    def walk(e):
        kinds.append(type(e).__name__)
        [walk(c) for c in e.children]
    walk(root)
    assert "ShuffleExchangeExec" not in kinds, kinds
    assert any(k == "HashAggregateExec" for k in kinds)
    d = g.to_pydict()
    ref = t.group_by(["k"]).aggregate([("v", "sum"), ("v", "count")])
    rows = {(k,): (sv, c) for k, sv, c in zip(
        ref["k"].to_pylist(), ref["v_sum"].to_pylist(),
        ref["v_count"].to_pylist())}
    _cmp(d, rows, ["k"], ["s", "c"])
