"""Differential tests for non-lambda array collection operations
(reference collectionOperations.scala scope)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


def _arrays(n=70, seed=13, lo=-20, hi=20, null_p=0.12):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        if rng.random() < 0.1:
            rows.append(None)
            continue
        ln = int(rng.integers(0, 7))
        rows.append([None if rng.random() < null_p else int(v)
                     for v in rng.integers(lo, hi, ln)])
    return rows


def _tbl(n=70, seed=13):
    rng = np.random.default_rng(seed)
    return pa.table({
        "a": pa.array(_arrays(n, seed), pa.list_(pa.int64())),
        "b": pa.array(_arrays(n, seed + 1), pa.list_(pa.int64())),
        "v": pa.array(rng.integers(-20, 20, n).astype(np.int64)),
        "s": pa.array(rng.integers(-3, 4, n).astype(np.int32)),
        "l": pa.array(rng.integers(0, 5, n).astype(np.int32)),
    })


def test_array_min_max(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl()).select(
            F.array_min(col("a")).alias("mn"),
            F.array_max(col("a")).alias("mx")),
        session)


def test_array_min_max_float_nan(session):
    rows = [[1.5, float("nan"), -2.0], [float("nan")], [], None, [3.25]]
    t = pa.table({"a": pa.array(rows, pa.list_(pa.float64()))})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.array_min(col("a")).alias("mn"),
            F.array_max(col("a")).alias("mx")),
        session)


def test_array_position_and_remove(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl()).select(
            F.array_position(col("a"), col("v")).alias("p"),
            F.array_position(col("a"), lit(7)).alias("p7"),
            F.array_remove(col("a"), col("v")).alias("r")),
        session)


def test_slice(session):
    t = _tbl()
    # start must be nonzero and length nonnegative for the valid path
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.slice(col("a"), F.when(col("s") == lit(0), lit(1))
                    .otherwise(col("s")), col("l")).alias("sl"),
            F.slice(col("a"), lit(2), lit(2)).alias("s22"),
            F.slice(col("a"), lit(-2), lit(3)).alias("sneg")),
        session)


def test_sort_array(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl()).select(
            F.sort_array(col("a")).alias("sa"),
            F.sort_array(col("a"), asc=False).alias("sd")),
        session)


def test_flatten(session):
    rng = np.random.default_rng(2)
    rows = []
    for _ in range(50):
        if rng.random() < 0.1:
            rows.append(None)
            continue
        outer = []
        for _ in range(int(rng.integers(0, 4))):
            if rng.random() < 0.1:
                outer.append(None)
            else:
                outer.append([int(v) for v in
                              rng.integers(-9, 9, int(rng.integers(0, 4)))])
        rows.append(outer)
    t = pa.table({"aa": pa.array(rows, pa.list_(pa.list_(pa.int64())))})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.flatten(col("aa")).alias("f")),
        session)


def test_array_distinct(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl(seed=40)).select(
            F.array_distinct(col("a")).alias("d")),
        session)


def test_array_set_ops(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl(seed=41)).select(
            F.array_union(col("a"), col("b")).alias("u"),
            F.array_intersect(col("a"), col("b")).alias("i"),
            F.array_except(col("a"), col("b")).alias("e")),
        session)


def test_arrays_overlap(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl(seed=42)).select(
            F.arrays_overlap(col("a"), col("b")).alias("o")),
        session)
