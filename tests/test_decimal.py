"""Decimal arithmetic/aggregation tests (reference decimalExpressions.scala
/ DecimalUtils; this engine implements decimal as scaled int64, precision
<= 18 — wider decimals are a documented limitation)."""
import decimal

import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect
from data_gen import DecimalGen, RepeatSeqGen, IntegerGen, gen_df

D = decimal.Decimal


@pytest.fixture
def session():
    return TpuSession()


def _df(s):
    return s.create_dataframe(pa.table({
        "k": pa.array(["a", "b", "a", "b", None]),
        "d": pa.array([D("1.25"), D("-3.50"), None, D("100.75"), D("0.01")],
                      pa.decimal128(10, 2)),
        "e": pa.array([D("0.5"), D("2.0"), D("1.5"), D("-1.0"), D("0.0")],
                      pa.decimal128(8, 1)),
        "i": pa.array([2, 3, 4, 5, 6], pa.int32()),
    }))


def test_decimal_cross_scale_arithmetic(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            (col("d") + col("e")).alias("add"),
            (col("d") - col("e")).alias("sub"),
            (col("d") * col("e")).alias("mul"),
            (col("d") + col("i")).alias("addi"),
            (col("d") * col("i")).alias("muli"),
            (col("d") / col("e")).alias("div")),
        session, approx_float=1e-12)


def test_decimal_exact_values(session):
    out = _df(session).select(
        (col("d") + col("e")).alias("a"),
        (col("d") * col("e")).alias("m")).to_pydict()
    assert out["a"][0] == D("1.75")
    assert out["m"][0] == D("0.625")
    assert out["m"][3] == D("-100.750")


def test_decimal_aggregates(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).group_by(col("k")).agg(
            F.sum("d").alias("s"), F.min("d").alias("mn"),
            F.max("d").alias("mx"), F.avg("d").alias("av"),
            F.count("d").alias("n")),
        session, ignore_order=True, approx_float=1e-12)


def test_decimal_compare_sort_distinct(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).filter(col("d") > col("e")).select(col("d")),
        session, ignore_order=True)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).order_by(col("d").asc_nulls_first()),
        session)


def test_decimal_generated(session):
    spec = [("k", RepeatSeqGen(IntegerGen(min_val=0, max_val=10), length=8)),
            ("d", DecimalGen(8, 3)), ("e", DecimalGen(5, 1))]
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=1024, seed=113)
        .select(col("k"), (col("d") + col("e")).alias("a"),
                (col("d") * col("e")).alias("m"))
        .group_by(col("k")).agg(F.sum("a").alias("sa"),
                                F.min("m").alias("mm")),
        session, ignore_order=True)


def test_decimal_cast_roundtrips(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _df(s).select(
            col("d").cast(T.FLOAT64).alias("f"),
            col("d").cast(T.DecimalType(14, 4)).alias("wide"),
            col("d").cast(T.DecimalType(6, 0)).alias("narrow"),
            col("i").cast(T.DecimalType(10, 2)).alias("fromint")),
        session, approx_float=1e-12)
