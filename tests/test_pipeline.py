"""Pipelined batch execution (runtime/pipeline.py): overlap, cancellation,
error propagation, retry interaction, TaskContext attribution, and the
pipeline.enabled=false == synchronous-path contract."""
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.runtime.pipeline import PipelinedIterator
from spark_rapids_tpu.runtime.task import TaskContext
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSession


def _table(rows, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": rng.integers(0, 40, rows),
        "v": rng.integers(-1000, 1000, rows),
        "d": rng.uniform(0, 1, rows),
    })


def _session(**conf):
    base = {"spark.rapids.sql.reader.batchSizeRows": "1024"}
    base.update(conf)
    return TpuSession(base)


def _non_pool_threads():
    """Live threads the pipeline could have leaked. Pool workers are
    excluded by name; so are the obs endpoint's short-lived
    `rapids-obs-probe` daemons — a probe that already finished its one
    dispatch can linger in threading.enumerate() until reaped under
    load (a known tier-1 flake), and the symmetric race (a probe alive
    at the `before` snapshot finishing by `after`) fails the set
    equality the other way, which no dead-thread filter can fix. Probe
    threads are the obs endpoint's concern and are leak-covered in
    tests/test_obs.py; these assertions guard PIPELINE threads.
    Threads that already terminated are filtered out before
    counting."""
    out = set()
    for t in threading.enumerate():
        if t.name.startswith(("rapids-host-pool", "rapids-obs-probe")):
            continue
        if not t.is_alive():
            continue
        out.add(t)
    return out


# ---------------------------------------------------------------------------
# PipelinedIterator unit behavior
# ---------------------------------------------------------------------------

def test_iterator_overlap_wall_clock():
    """depth>=1 overlaps producer and consumer work: wall clock of a
    5x(50ms produce + 50ms consume) loop must land well under the 500ms
    serial sum (and the sync depth-0 control must not)."""
    def src():
        for i in range(5):
            time.sleep(0.05)
            yield i

    t0 = time.monotonic()
    pit = PipelinedIterator(src(), depth=2)
    got = []
    for item in pit:
        time.sleep(0.05)
        got.append(item)
    pit.close()
    overlapped = time.monotonic() - t0
    assert got == list(range(5))
    assert overlapped < 0.42, overlapped  # serial would be >= 0.5

    t0 = time.monotonic()
    got = []
    for item in src():
        time.sleep(0.05)
        got.append(item)
    serial = time.monotonic() - t0
    assert serial >= 0.45
    assert overlapped < serial


def test_iterator_preserves_order_and_count():
    pit = PipelinedIterator(iter(range(257)), depth=3)
    assert list(pit) == list(range(257))
    pit.close()


def test_iterator_producer_exception_propagates():
    def src():
        yield 1
        yield 2
        raise ValueError("decode exploded")

    pit = PipelinedIterator(src(), depth=2)
    got = []
    with pytest.raises(ValueError, match="decode exploded"):
        for item in pit:
            got.append(item)
    pit.close()
    assert got == [1, 2]


def test_iterator_early_close_cancels_producer():
    """Closing mid-stream must stop production promptly, run the source
    generator's finally (GeneratorExit delivered), and leave no threads
    beyond the shared pool's workers."""
    state = {"produced": 0, "closed": False}

    def src():
        try:
            for i in range(10_000):
                state["produced"] += 1
                yield i
        finally:
            state["closed"] = True

    before = _non_pool_threads()
    pit = PipelinedIterator(src(), depth=2)
    it = iter(pit)
    assert next(it) == 0
    assert next(it) == 1
    pit.close()
    assert state["closed"], "source generator finally did not run"
    # bounded lookahead: the producer cannot have raced far past the
    # queue depth + one stashed item + the two we took
    assert state["produced"] <= 2 + 2 + 2
    assert _non_pool_threads() == before


def test_iterator_taskcontext_binding():
    """The producer runs on a pool worker but must see the CONSUMER
    task's thread-local TaskContext (semaphore re-entrancy, retry and
    metric attribution all key off it)."""
    seen = {}

    def src():
        seen["ctx"] = TaskContext.peek()
        seen["thread"] = threading.current_thread().name
        yield 1

    with TaskContext(partition_id=3) as ctx:
        pit = PipelinedIterator(src(), depth=1, ctx=ctx)
        assert list(pit) == [1]
        pit.close()
    assert seen["ctx"] is ctx
    assert seen["thread"].startswith("rapids-host-pool")


def test_iterator_pool_worker_context_restored():
    """A refill must not leak the task binding into the pool worker it
    borrowed: the next task the worker runs sees its own context."""
    from spark_rapids_tpu.runtime.host_pool import get_host_pool
    with TaskContext() as ctx:
        pit = PipelinedIterator(iter([1, 2, 3]), depth=1, ctx=ctx)
        assert list(pit) == [1, 2, 3]
        pit.close()
    # drain every worker: none may still carry the finished task
    pool = get_host_pool()
    futs = [pool.submit(TaskContext.peek) for _ in range(pool.n_threads * 2)]
    assert all(f.result() is not ctx for f in futs)


# ---------------------------------------------------------------------------
# end-to-end: planner pass + queries
# ---------------------------------------------------------------------------

def _norm(tbl):
    d = tbl.to_pydict()
    keys = sorted(d)
    return sorted(zip(*[
        [round(v, 9) if isinstance(v, float) else v for v in d[k]]
        for k in keys]))


def test_pipelined_query_matches_sync():
    t = _table(30_000)

    def q(s):
        return (s.create_dataframe(t, num_partitions=2)
                .filter(col("v") > lit(-500))
                .group_by("k").agg(F.sum(col("v")).alias("sv"),
                                   F.count().alias("n")))

    r_pipe = q(_session()).collect()
    r_sync = q(_session(**{
        "spark.rapids.sql.pipeline.enabled": "false"})).collect()
    assert _norm(r_pipe) == _norm(r_sync)


def test_depth_zero_equals_synchronous_plan_and_results():
    """depth=0 must not only match results — it must BE the synchronous
    plan: no PipelineExec node is inserted at all."""
    t = _table(8_000)

    def tree_classes(s, df):
        from spark_rapids_tpu.plan.overrides import convert_plan
        root, _ = convert_plan(df.plan, s.conf)
        names = []

        def walk(n):
            names.append(type(n).__name__)
            for c in n.children:
                walk(c)
        walk(root)
        return names

    s0 = _session(**{"spark.rapids.sql.pipeline.depth": "0"})
    df0 = s0.create_dataframe(t).filter(col("v") > lit(0))
    assert "PipelineExec" not in tree_classes(s0, df0)
    s1 = _session()
    df1 = s1.create_dataframe(t).filter(col("v") > lit(0))
    assert "PipelineExec" in tree_classes(s1, df1)
    assert _norm(df0.collect()) == _norm(df1.collect())


def test_dispatch_budget_unchanged_by_pipelining():
    """Pipelining moves host work off the critical path; it must not
    change WHAT is dispatched (the fuse hook counts every device entry
    issued through fused())."""
    from spark_rapids_tpu.exec import fuse
    t = _table(16_000)

    def run(enabled):
        counts = []
        fuse.set_dispatch_hook(lambda key: counts.append(key))
        try:
            s = _session(**{
                "spark.rapids.sql.pipeline.enabled": str(enabled).lower()})
            out = (s.create_dataframe(t, num_partitions=1)
                   .filter(col("d") < lit(0.9))
                   .select(col("k"), (col("v") * lit(2)).alias("v2"))
                   .group_by("k").agg(F.sum(col("v2")).alias("s")))
            res = out.collect()
        finally:
            fuse.set_dispatch_hook(None)
        return res, len(counts)

    r1, n1 = run(True)
    r2, n2 = run(False)
    assert _norm(r1) == _norm(r2)
    assert n1 == n2


def test_trace_shows_producer_consumer_overlap(tmp_path):
    """The DEBUG trace carries pipelineProduce spans from the producer
    side; with a bounded queue their intervals must interleave with (not
    strictly precede) consumer-side exec spans — the overlap the whole
    layer exists to create."""
    import json
    t = _table(60_000)
    s = _session(**{
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.path": str(tmp_path),
        "spark.rapids.sql.trace.level": "DEBUG",
    })
    out = (s.create_dataframe(t, num_partitions=1)
           .filter(col("v") > lit(-900))
           .group_by("k").agg(F.sum(col("v")).alias("sv")))
    out.collect()
    assert s.last_trace_paths is not None
    with open(s.last_trace_paths["trace"]) as f:
        events = json.load(f)["traceEvents"]
    produce = [(e["ts"], e["ts"] + e["dur"]) for e in events
               if e.get("name") == "pipelineProduce"]
    consume = [(e["ts"], e["ts"] + e["dur"]) for e in events
               if e.get("ph") == "X" and "HashAggregate" in e.get("name", "")]
    assert produce, "no pipelineProduce spans in DEBUG trace"
    assert consume, "no consumer-side agg spans in trace"
    # overlap: some batch was produced AFTER consumption began (the
    # bounded queue forces the producer to wait for the consumer)
    first_consume = min(ts for ts, _ in consume)
    assert max(ts for ts, _ in produce) > first_consume


def test_producer_error_fails_query(tmp_path):
    """A decode failure on the producer thread must surface as the
    query's exception, not hang or get swallowed."""
    import pyarrow.parquet as pq
    path = str(tmp_path / "t.parquet")
    pq.write_table(_table(4_000), path, row_group_size=256)
    s = _session()
    df = s.read_parquet(path).filter(col("v") > lit(0))
    with open(path, "wb") as f:
        f.write(b"not a parquet file at all")
    before = _non_pool_threads()
    with pytest.raises(Exception):
        df.collect()
    assert _non_pool_threads() == before


def test_limit_early_exit_no_thread_leak():
    t = _table(200_000)
    s = _session()
    before = _non_pool_threads()
    r = (s.create_dataframe(t)
         .filter(col("d") >= lit(0.0)).limit(7).collect())
    assert r.num_rows == 7
    assert _non_pool_threads() == before
    # the pipeline actually engaged AND stopped early: far fewer batches
    # crossed the boundary than the ~196 the input holds
    lm = s.last_metrics()
    pipe = next(v for k, v in lm.items() if k.startswith("PipelineExec"))
    assert pipe["pipelineDepth"] >= 1
    assert pipe["numOutputBatches"] < 50


def test_retry_oom_through_pipelined_stage():
    """injectRetryOOM firing under a pipelined scan->agg stage must
    drain/replay exactly as in the synchronous path and converge to the
    same result."""
    from spark_rapids_tpu import config as C
    t = _table(20_000)

    def q(s):
        return (s.create_dataframe(t, num_partitions=2)
                .group_by("k").agg(F.sum(col("v")).alias("sv"),
                                   F.count().alias("n")))

    expected = _norm(q(_session(**{
        "spark.rapids.sql.pipeline.enabled": "false"})).collect())
    got = _norm(q(_session(**{
        C.RETRY_OOM_INJECT.key: "3"})).collect())
    assert got == expected


def test_pipelined_serialized_shuffle_matches_sync():
    """The streaming ThrottlingExecutor writer (pipeline on) must produce
    byte-identical shuffle results to the synchronous serde path."""
    t = _table(24_000)

    def q(s):
        # multi-partition group_by plans partial-agg -> ShuffleExchange
        # (the test backend exposes 8 virtual devices, so the planner
        # takes the exchange path, not the collected single-device one)
        return (s.create_dataframe(t, num_partitions=4)
                .group_by("k").agg(F.count().alias("n"),
                                   F.sum(col("v")).alias("sv")))

    conf = {"spark.rapids.shuffle.mode": "SERIALIZED",
            "spark.rapids.shuffle.multiThreaded.writer.threads": "4"}
    r_pipe = q(_session(**conf)).collect()
    r_sync = q(_session(**dict(
        conf, **{"spark.rapids.sql.pipeline.enabled": "false"}))).collect()
    assert _norm(r_pipe) == _norm(r_sync)


def test_deferred_offsets_fetch_matches_sync():
    """Compact exchange with the one-deep deferred offsets window must
    emit exactly the sub-batches (contents AND per-partition row order)
    the eager dispatch-then-fetch loop emits."""
    from spark_rapids_tpu.columnar.batch import to_arrow
    from spark_rapids_tpu.exec import tpu_nodes as X
    from spark_rapids_tpu.plan.nodes import bind_expr
    from spark_rapids_tpu.plan.overrides import convert_plan
    t = _table(6_000)

    def drain(enabled):
        s = _session(**{
            "spark.rapids.sql.pipeline.enabled": str(enabled).lower()})
        df = s.create_dataframe(t, num_partitions=3)
        child, _ = convert_plan(df.plan, s.conf)
        ex = X.ShuffleExchangeExec(
            df.plan, [child], s.conf,
            [bind_expr(col("k"), df.plan.schema)], n_out=4)
        parts = []
        for p in range(ex.num_partitions):
            with TaskContext(partition_id=p) as ctx:
                parts.append([to_arrow(b, df.plan.schema.names).to_pylist()
                              for b in ex.execute_partition(ctx, p)])
        return parts

    assert drain(True) == drain(False)


# ---------------------------------------------------------------------------
# TrafficController stall diagnostic (io/async_io.py satellite)
# ---------------------------------------------------------------------------

def test_traffic_controller_stall_warning(caplog):
    import logging

    from spark_rapids_tpu.io.async_io import TrafficController
    ctrl = TrafficController(100, stall_warn_s=0.05)
    ctrl.acquire(80)
    release = threading.Timer(0.25, ctrl.release, args=(80,))
    release.start()
    with caplog.at_level(logging.WARNING, logger="spark_rapids_tpu"):
        t0 = time.monotonic()
        ctrl.acquire(80)  # blocks past the 50ms warn threshold
        waited = time.monotonic() - t0
    release.join()
    ctrl.release(80)
    assert waited >= 0.2  # admission semantics unchanged: it WAITED
    assert any("async write throttle stalled" in r.message
               for r in caplog.records)
    # exactly one warning per acquire, however long the wait
    assert sum("async write throttle stalled" in r.message
               for r in caplog.records) == 1


def test_traffic_controller_no_warning_below_threshold(caplog):
    import logging

    from spark_rapids_tpu.io.async_io import TrafficController
    ctrl = TrafficController(100, stall_warn_s=5.0)
    ctrl.acquire(80)
    threading.Timer(0.05, ctrl.release, args=(80,)).start()
    with caplog.at_level(logging.WARNING, logger="spark_rapids_tpu"):
        ctrl.acquire(80)
    ctrl.release(80)
    assert not any("stalled" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# producer-thread faults under the pipelined path (runtime/faults.py)
# ---------------------------------------------------------------------------

def test_injected_producer_death_fails_cleanly_no_leak():
    """A pipeline.producer fault killing the refill must surface as the
    query's exception (fallback off), with no leaked refill threads and
    the pipeline boundary provably engaged."""
    from spark_rapids_tpu.runtime.faults import InjectedFaultError
    t = _table(60_000)
    s = _session(**{"spark.rapids.debug.faults":
                    "pipeline.producer:ioerror:1,3"})
    df = (s.create_dataframe(t, num_partitions=1)
          .filter(col("v") > lit(-900))
          .group_by("k").agg(F.sum(col("v")).alias("sv")))
    before = _non_pool_threads()
    with pytest.raises(InjectedFaultError):
        df.collect()
    assert s.last_action_status == ("failed", None)
    time.sleep(0.2)
    assert _non_pool_threads() == before


def test_injected_producer_death_degrades_with_correct_results():
    """Same producer death with CPU fallback on: the query must end
    degraded with results identical to the clean run."""
    t = _table(60_000)

    def q(s):
        return (s.create_dataframe(t, num_partitions=1)
                .filter(col("v") > lit(-900))
                .group_by("k").agg(F.sum(col("v")).alias("sv")))

    expected = _norm(q(_session()).collect())
    s = _session(**{"spark.rapids.fallback.cpu.enabled": "true",
                    "spark.rapids.debug.faults":
                    "pipeline.producer:ioerror:1,3"})
    before = _non_pool_threads()
    got = _norm(q(s).collect())
    assert s.last_action_status == ("degraded", "InjectedFaultError")
    assert got == expected
    time.sleep(0.2)
    assert _non_pool_threads() == before


def test_shuffle_read_corruption_recovers_under_pipelined_path():
    """One-shot shuffle.read corruption with the pipelined SERIALIZED
    writer engaged: the blob re-fetch must recover transparently and the
    result must match the clean pipelined run."""
    t = _table(24_000)
    conf = {"spark.rapids.shuffle.mode": "SERIALIZED",
            "spark.rapids.shuffle.multiThreaded.writer.threads": "4"}

    def q(s):
        return (s.create_dataframe(t, num_partitions=4)
                .group_by("k").agg(F.count().alias("n"),
                                   F.sum(col("v")).alias("sv")))

    expected = _norm(q(_session(**conf)).collect())
    s = _session(**dict(conf, **{
        "spark.rapids.debug.faults": "shuffle.read:corrupt:1"}))
    before = _non_pool_threads()
    got = _norm(q(s).collect())
    assert s.last_action_status == ("ok", None)
    assert got == expected
    time.sleep(0.2)
    assert _non_pool_threads() == before


def test_shuffle_read_persistent_corruption_fails_cleanly():
    """Corruption on BOTH the read and its re-fetch must fail the query
    (fallback off) without hanging or leaking refill threads."""
    from spark_rapids_tpu.shuffle.serde import ShuffleCorruptionError
    t = _table(24_000)
    # count 99 = PERSISTENT corruption: every read AND every re-fetch
    # corrupts, so recovery must give up after its single retry (small
    # counts can spread over concurrent partition tasks' reads, each
    # recovering independently)
    s = _session(**{"spark.rapids.shuffle.mode": "SERIALIZED",
                    "spark.rapids.debug.faults": "shuffle.read:corrupt:99"})
    df = (s.create_dataframe(t, num_partitions=4)
          .group_by("k").agg(F.sum(col("v")).alias("sv")))
    before = _non_pool_threads()
    with pytest.raises(ShuffleCorruptionError):
        df.collect()
    time.sleep(0.2)
    assert _non_pool_threads() == before
