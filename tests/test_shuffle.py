"""Shuffle subsystem tests: kudo-analog serde, spillable store, SERIALIZED
exchange mode, range partitioning, cross-process exchange.

Reference parity: GpuColumnarBatchSerializer / kudo wire format,
ShuffleBufferCatalog spill, RapidsShuffleThreadedWriter files,
GpuRangePartitioner (§2.7, §2.11).
"""
import math
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.columnar.batch import from_arrow, to_arrow
from spark_rapids_tpu.shuffle import serde
from spark_rapids_tpu.shuffle.store import ShuffleStore

from asserts import assert_tpu_and_cpu_are_equal_collect
from data_gen import (
    IntegerGen, LongGen, DoubleGen, StringGen, ArrayGen, StructGen,
    RepeatSeqGen, gen_table, gen_df,
)


@pytest.fixture
def session():
    return TpuSession()


def _rt_table():
    return pa.table({
        "i": pa.array([1, 2, None, 4], pa.int64()),
        "f": pa.array([1.5, float("nan"), None, -0.0]),
        "s": pa.array(["aa", None, "ccc", "dd"]),
        "a": pa.array([[1, 2], None, [], [3]], pa.list_(pa.int32())),
        "st": pa.array([{"x": 1}, None, {"x": 3}, {"x": 4}],
                       pa.struct([("x", pa.int64())])),
        "m": pa.array([[("k", 1.0)], [], None, [("a", 2.0)]],
                      pa.map_(pa.string(), pa.float64())),
    })


def _eq(a, b):
    if isinstance(a, float) and isinstance(b, float):
        return (math.isnan(a) and math.isnan(b)) or a == b
    if isinstance(a, dict):
        return set(a) == set(b) and all(_eq(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(_eq(x, y) for x, y in zip(a, b))
    return a == b


@pytest.mark.parametrize("codec", ["auto", "zstd", "zlib", "none"])
def test_serde_roundtrip(codec):
    if codec == "zstd":
        # explicit zstd requires the optional zstandard package; the
        # engine's default is 'auto' (zstd when available, else zlib)
        pytest.importorskip("zstandard")
    t = _rt_table()
    b = from_arrow(t)
    data = serde.serialize_batch(b, codec)
    back = to_arrow(serde.deserialize_batch(data), t.schema.names)
    assert _eq(back.to_pylist(), t.to_pylist())


def test_serde_roundtrip_generated():
    spec = [("k", RepeatSeqGen(IntegerGen(), length=9)),
            ("v", LongGen()), ("d", DoubleGen()),
            ("s", StringGen()), ("a", ArrayGen(LongGen())),
            ("st", StructGen([("p", IntegerGen()), ("q", StringGen())]))]
    t = gen_table(spec, length=1000, seed=61)
    b = from_arrow(t)
    back = to_arrow(serde.deserialize_batch(serde.serialize_batch(b)),
                    t.schema.names)
    assert _eq(back.to_pylist(), t.to_pylist())


def test_serde_python_fallback_identical_frames():
    import spark_rapids_tpu.native as N
    b = from_arrow(_rt_table())
    native = serde.serialize_batch(b, "none")
    saved = (N._KUDO_LIB, N._KUDO_FAILED)
    try:
        N._KUDO_LIB, N._KUDO_FAILED = None, True
        pyframe = serde.serialize_batch(b, "none")
        assert pyframe == native  # the format is the contract
        back = to_arrow(serde.deserialize_batch(native),
                        _rt_table().schema.names)
        assert _eq(back.to_pylist(), _rt_table().to_pylist())
    finally:
        N._KUDO_LIB, N._KUDO_FAILED = saved


def test_serde_checksum_detects_corruption():
    b = from_arrow(_rt_table())
    data = bytearray(serde.serialize_batch(b, "none"))
    data[len(data) // 2] ^= 0xFF
    with pytest.raises(ValueError):
        serde.deserialize_batch(bytes(data))


def test_store_spills_to_disk(tmp_path):
    store = ShuffleStore(4, host_budget_bytes=1000, spill_dir=str(tmp_path))
    blobs = {p: [os.urandom(400) for _ in range(3)] for p in range(4)}
    for p, bl in blobs.items():
        for b in bl:
            store.add(p, b)
    assert store.bytes_spilled > 0
    for p in range(4):
        assert list(store.iter_partition(p)) == blobs[p]
    store.close()


@pytest.mark.parametrize("budget", [None, 2048])
def test_serialized_exchange_differential(budget):
    conf = {"spark.rapids.shuffle.mode": "SERIALIZED"}
    if budget:
        conf["spark.rapids.shuffle.hostSpillBudget"] = budget
    s = TpuSession(conf)
    spec = [("k", RepeatSeqGen(IntegerGen(min_val=0, max_val=40), length=30)),
            ("v", LongGen(min_val=-(1 << 40), max_val=1 << 40)),
            ("s", StringGen())]
    assert_tpu_and_cpu_are_equal_collect(
        lambda ss: gen_df(ss, spec, length=2000, seed=67, num_partitions=4)
        .group_by(col("k")).agg(F.sum("v").alias("sv"),
                                F.count().alias("n")),
        s, ignore_order=True)


def test_serialized_exchange_join():
    s = TpuSession({"spark.rapids.shuffle.mode": "SERIALIZED"})
    lspec = [("k", RepeatSeqGen(IntegerGen(min_val=0, max_val=50), length=40)),
             ("lv", LongGen())]
    rspec = [("k", RepeatSeqGen(IntegerGen(min_val=25, max_val=75), length=35)),
             ("rv", DoubleGen(no_nans=True))]
    assert_tpu_and_cpu_are_equal_collect(
        lambda ss: gen_df(ss, lspec, length=800, seed=71, num_partitions=3)
        .join(gen_df(ss, rspec, length=600, seed=73, num_partitions=3),
              on="k", how="full"),
        s, ignore_order=True)


@pytest.mark.parametrize("orders", [
    lambda: [col("a").asc_nulls_first(), col("b").desc()],
    lambda: [col("a").desc_nulls_last()],
    lambda: [col("f").asc()],
])
def test_range_partitioned_global_sort(session, orders):
    spec = [("a", IntegerGen(min_val=-500, max_val=500)),
            ("b", LongGen(min_val=0, max_val=1 << 30)),
            ("f", DoubleGen())]
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=3000, seed=79, num_partitions=4)
        .order_by(*orders()),
        session)


def test_range_sort_keeps_partitions(session):
    # the point of range partitioning: global sort without collapsing to
    # one partition
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.exec import tpu_nodes as X
    df = session.create_dataframe(
        pa.table({"a": pa.array(np.arange(100)[::-1])}),
        num_partitions=4).order_by(col("a"))
    root, _ = convert_plan(df.plan, session.conf)
    assert isinstance(root, X.SortExec)
    assert isinstance(root.children[0], X.RangeExchangeExec)
    assert root.num_partitions == 4


def test_cross_process_exchange(tmp_path, session):
    """A SEPARATE python process writes the hash-partitioned shuffle files;
    this process mounts them and completes the aggregation."""
    root = str(tmp_path / "xproc")
    writer = f"""
import jax
jax.config.update("jax_platforms", "cpu")
import pyarrow as pa
from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.shuffle.exchange_files import write_exchange
s = TpuSession()
t = pa.table({{'k': [i % 11 for i in range(700)],
               'v': list(range(700)),
               's': ['name%d' % (i % 5) for i in range(700)]}})
df = s.create_dataframe(t, num_partitions=3)
write_exchange(df, {root!r}, keys=['k'], n_out=4)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", writer], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.path.exists(os.path.join(root, "manifest.json"))

    from spark_rapids_tpu.shuffle.exchange_files import read_exchange
    df = read_exchange(session, root)
    assert df.plan.schema.names == ["k", "v", "s"]
    out = df.group_by(col("k")).agg(F.sum("v").alias("sv"),
                                    F.count().alias("n")).to_pydict()
    exp = {}
    for i in range(700):
        exp.setdefault(i % 11, [0, 0])
        exp[i % 11][0] += i
        exp[i % 11][1] += 1
    got = {k: [sv, n] for k, sv, n in zip(out["k"], out["sv"], out["n"])}
    assert got == exp
    # co-partitioning: every key lands in exactly one reduce partition
    from spark_rapids_tpu.shuffle.exchange_files import read_partition_batches
    seen = {}
    for r in range(4):
        for b in read_partition_batches(root, r):
            for k in to_arrow(b, ["k", "v", "s"]).to_pydict()["k"]:
                assert seen.setdefault(k, r) == r
