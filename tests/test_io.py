"""I/O tests: CSV/JSON/ORC scans, writer roundtrips, dynamic partitioning,
write modes, async throttle (reference csv_test.py / orc_test.py /
parquet_write_test.py style)."""
import os

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


def _t(n=50, seed=3):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(np.array(["a", "b", "c"], object)[rng.integers(0, 3, n)]),
        "i": pa.array(rng.integers(-100, 100, n).astype(np.int64)),
        "f": pa.array(np.round(rng.uniform(-5, 5, n), 4)),
    })


def test_parquet_write_read_roundtrip(session, tmp_path):
    t = _t()
    path = str(tmp_path / "out_parquet")
    session.create_dataframe(t, num_partitions=3).write.parquet(path)
    assert os.path.exists(os.path.join(path, "_SUCCESS"))
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path), session, ignore_order=True)
    back = session.read_parquet(path).collect()
    assert back.num_rows == t.num_rows


def test_csv_write_read_roundtrip(session, tmp_path):
    t = _t()
    path = str(tmp_path / "out_csv")
    session.create_dataframe(t).write.csv(path)
    df = session.read_csv(path)
    assert df.count() == t.num_rows
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_csv(path).group_by("k").agg(F.sum(col("i"))),
        session, ignore_order=True)


def test_orc_write_read_roundtrip(session, tmp_path):
    t = _t()
    path = str(tmp_path / "out_orc")
    session.create_dataframe(t).write.orc(path)
    assert session.read_orc(path).count() == t.num_rows


def test_json_write_read_roundtrip(session, tmp_path):
    t = _t(20)
    path = str(tmp_path / "out_json")
    session.create_dataframe(t).write.json(path)
    df = session.read_json(path)
    assert df.count() == 20
    got = df.agg(F.sum(col("i"))).to_pydict()
    assert list(got.values())[0][0] == sum(t["i"].to_pylist())


def test_partitioned_write_layout(session, tmp_path):
    t = _t()
    path = str(tmp_path / "out_part")
    session.create_dataframe(t).write.partition_by("k").parquet(path)
    subdirs = sorted(d for d in os.listdir(path) if d.startswith("k="))
    assert subdirs == ["k=a", "k=b", "k=c"]
    # reading a single partition dir yields only that key's rows
    one = session.read_parquet(os.path.join(path, "k=a"))
    expect = sum(1 for v in t["k"].to_pylist() if v == "a")
    assert one.count() == expect
    assert "k" not in one.columns  # partition col not duplicated in files


def test_write_modes(session, tmp_path):
    t = _t(10)
    path = str(tmp_path / "out_modes")
    df = session.create_dataframe(t)
    df.write.parquet(path)
    with pytest.raises(FileExistsError):
        df.write.parquet(path)
    df.write.mode("append").parquet(path)
    assert session.read_parquet(path).count() == 20
    df.write.mode("overwrite").parquet(path)
    assert session.read_parquet(path).count() == 10


def test_multifile_scan(session, tmp_path):
    path = str(tmp_path / "multi")
    session.create_dataframe(_t(40), num_partitions=4).write.parquet(path)
    df = session.read_parquet(path)
    # one partition per file
    assert df.count() == 40
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).filter(col("i") > lit(0)),
        session, ignore_order=True)


def test_csv_no_header(session, tmp_path):
    p = str(tmp_path / "raw.csv")
    with open(p, "w") as f:
        f.write("1,foo\n2,bar\n")
    df = session.read_csv(p, header=False)
    assert df.count() == 2
    assert len(df.columns) == 2


def test_traffic_controller_bounds_inflight():
    from spark_rapids_tpu.io.async_io import ThrottlingExecutor, TrafficController
    import threading
    import time
    tc = TrafficController(100)
    ex = ThrottlingExecutor(4, tc)
    peak = []

    def work():
        peak.append(tc.in_flight)
        time.sleep(0.01)

    fs = [ex.submit(60, work) for _ in range(6)]
    for f in fs:
        f.result()
    ex.shutdown()
    assert max(peak) <= 100  # never two 60-byte writes in flight
    assert tc.in_flight == 0


def test_partitioned_roundtrip_with_discovery(session, tmp_path):
    # write partition_by then read the ROOT back: hive discovery must
    # reconstruct the partition column (README quick-start pattern)
    t = _t()
    path = str(tmp_path / "disc")
    session.create_dataframe(t).write.partition_by("k").parquet(path)
    df = session.read_parquet(path)
    assert set(df.columns) >= {"i", "f", "k"}
    assert df.count() == t.num_rows
    got = {r["k"]: r["count"] for r in
           df.group_by("k").count().collect().to_pylist()}
    from collections import Counter
    assert got == dict(Counter(t["k"].to_pylist()))
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).group_by("k").agg(F.sum(col("i"))),
        session, ignore_order=True)


def test_partition_value_escaping(session, tmp_path):
    t = pa.table({"k": ["a/b", "c=d", "plain"], "v": [1, 2, 3]})
    path = str(tmp_path / "esc")
    session.create_dataframe(t).write.partition_by("k").parquet(path)
    import os
    dirs = sorted(d for d in os.listdir(path) if d.startswith("k="))
    assert all("/" not in d[2:] for d in dirs)
    back = session.read_parquet(path)
    assert sorted(back.select(col("k")).to_pydict()["k"]) == ["a/b", "c=d", "plain"]


def test_read_columns_reordered(session, tmp_path):
    # columns in non-file order must bind names to the right data
    path = str(tmp_path / "ord")
    session.create_dataframe(_t(10)).write.parquet(path)
    d = session.read_parquet(path, columns=["f", "k"])
    got = d.to_pydict()
    assert isinstance(got["f"][0], float)
    assert isinstance(got["k"][0], str)


def test_scan_coalesces_small_row_groups(session, tmp_path):
    # The planner inserts CoalesceBatchesExec over file scans
    # (insertCoalesce analog): 10 tiny row groups must reach the
    # downstream exec as one coalesced batch.
    import pyarrow.parquet as pq
    t = _t(100)
    path = str(tmp_path / "rg.parquet")
    pq.write_table(t, path, row_group_size=10)
    # PERFILE: no host-side coalescing, so the device coalesce node is
    # what merges the 10 per-row-group batches (this test pins the HOST
    # decode path — the device-decode source coalesces row groups itself
    # and never gets a CoalesceBatchesExec)
    session = TpuSession(
        {"spark.rapids.sql.format.parquet.reader.type": "PERFILE",
         "spark.rapids.sql.decode.device.enabled": "false"})
    df = session.read_parquet(path)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).filter(col("i") > lit(0)),
        session, ignore_order=True)
    # exec tree contains the coalesce node directly above the scan
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.exec import tpu_nodes as X
    root, _ = convert_plan(df.plan, session.conf)
    nodes = []
    def walk(e):
        nodes.append(e)
        for c in e.children:
            walk(c)
    walk(root)
    co = [n for n in nodes if isinstance(n, X.CoalesceBatchesExec)]
    assert co
    # the pipeline pass may insert its boundary between the two — the
    # scan still feeds the coalesce, just through the producer queue
    below = co[0].children[0]
    if type(below).__name__.startswith("PipelineExec"):
        below = below.children[0]
    assert isinstance(below, X.ParquetScanExec)
    # and it actually coalesces: downstream sees 1 batch, not 10
    from spark_rapids_tpu.runtime.task import TaskContext
    with TaskContext(partition_id=0) as tctx:
        out = list(co[0].execute_partition(tctx, 0))
    assert len(out) == 1
    assert co[0].metrics.metric("numInputBatches").value >= 10


def _rg_metrics(session):
    # footer pruning runs identically on the host scan and the
    # device-decode encoded source — accept whichever the conf picked
    m = session.last_metrics()
    scan = next(v for k, v in m.items()
                if k.startswith(("ParquetScanExec",
                                 "EncodedParquetSourceExec")))
    return scan.get("numRowGroups", 0), scan.get("numRowGroupsPruned", 0)


def test_parquet_row_group_pruning(session, tmp_path):
    # A sorted column gives disjoint per-row-group [min,max] ranges; a
    # selective filter must skip the refuted groups by footer stats alone
    # (GpuParquetScan.scala filterBlocks analog) and still agree with the
    # CPU baseline exactly.
    import pyarrow.parquet as pq
    n = 200
    t = pa.table({
        "i": pa.array(np.arange(n).astype(np.int64)),
        "s": pa.array([f"key{j:04d}" for j in range(n)]),
        "f": pa.array(np.linspace(-5.0, 5.0, n)),
    })
    path = str(tmp_path / "sorted.parquet")
    pq.write_table(t, path, row_group_size=20)

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).filter(col("i") >= lit(150)),
        session, ignore_order=True)
    total, pruned = _rg_metrics(session)
    assert total == 10 and pruned == 7  # groups 0..6 statically refuted

    # conjunction narrows to one group; projection renames still push
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path)
        .select(col("i").alias("j"), col("f"))
        .filter((col("j") >= lit(40)) & (col("j") < lit(60))),
        session, ignore_order=True)
    total, pruned = _rg_metrics(session)
    assert (total, pruned) == (10, 9)

    # string stats prune too
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).filter(col("s") == lit("key0105")),
        session, ignore_order=True)
    total, pruned = _rg_metrics(session)
    assert (total, pruned) == (10, 9)

    # disjunction keeps the union of candidate groups
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path)
        .filter((col("i") < lit(20)) | (col("i") >= lit(180))),
        session, ignore_order=True)
    total, pruned = _rg_metrics(session)
    assert (total, pruned) == (10, 8)


def test_parquet_pruning_shared_scan_branches(session, tmp_path):
    # One ParquetScan object consumed by two differently-filtered branches
    # (union of views over the same DataFrame): the branch predicates must
    # NOT conjoin — that statically refutes groups each branch needs.
    # Regression: pushdown keyed by id(scan) used to merge both branches.
    import pyarrow.parquet as pq
    n = 200
    t = pa.table({"i": pa.array(np.arange(n).astype(np.int64))})
    path = str(tmp_path / "shared.parquet")
    pq.write_table(t, path, row_group_size=20)

    assert_tpu_and_cpu_are_equal_collect(
        lambda s: (lambda df: df.filter(col("i") >= lit(150))
                   .union(df.filter(col("i") < lit(20))))(s.read_parquet(path)),
        session, ignore_order=True)
    # the OR of the branches still prunes the middle groups
    total, pruned = _rg_metrics(session)
    assert total >= 10 and pruned >= total // 2

    # a branch with no filter at all disables pruning for the shared scan
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: (lambda df: df.filter(col("i") >= lit(150)).union(df))(
            s.read_parquet(path)),
        session, ignore_order=True)
    total, pruned = _rg_metrics(session)
    assert pruned == 0


def test_parquet_pruning_nulls_and_unpushable(session, tmp_path):
    import pyarrow.parquet as pq
    t = pa.table({
        "a": pa.array([1, 2, 3, 4] * 5 + [None] * 20, pa.int64()),
        "b": pa.array(list(range(40)), pa.int64()),
    })
    path = str(tmp_path / "nulls.parquet")
    pq.write_table(t, path, row_group_size=20)
    # IS NULL refutes the null-free first group
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).filter(F.isnull(col("a"))),
        session, ignore_order=True)
    total, pruned = _rg_metrics(session)
    assert (total, pruned) == (2, 1)
    # IS NOT NULL refutes the all-null second group
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).filter(~F.isnull(col("a"))),
        session, ignore_order=True)
    total, pruned = _rg_metrics(session)
    assert (total, pruned) == (2, 1)
    # an unpushable predicate (arithmetic) reads everything, correctly
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).filter((col("b") % lit(7)) == lit(0)),
        session, ignore_order=True)
    total, pruned = _rg_metrics(session)
    assert (total, pruned) == (2, 0)


def test_parquet_partition_file_pruning(session, tmp_path):
    # hive-layout partition values prune whole files before any footer read
    path = str(tmp_path / "pt")
    t = _t(60)
    session.create_dataframe(t).write.partition_by("k").parquet(path)
    df = session.read_parquet(path).filter(col("k") == lit("b"))
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_parquet(path).filter(col("k") == lit("b")),
        session, ignore_order=True)
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.exec import tpu_nodes as X
    root, _ = convert_plan(df.plan, session.conf)
    def find(e):
        # both scan flavors prune partition files in their ctor
        if isinstance(e, (X.ParquetScanExec, X.EncodedParquetSourceExec)):
            return e
        for c in e.children:
            r = find(c)
            if r is not None:
                return r
    scan = find(root)
    assert scan is not None
    assert len(scan._kept_files) < len(scan.plan.paths)


@pytest.mark.parametrize("mode", ["PERFILE", "MULTITHREADED", "COALESCING"])
def test_parquet_reader_strategies(tmp_path, mode):
    import pyarrow.parquet as pq
    s = TpuSession({"spark.rapids.sql.format.parquet.reader.type": mode})
    t = _t(120)
    path = str(tmp_path / "modes.parquet")
    pq.write_table(t, path, row_group_size=10)
    assert_tpu_and_cpu_are_equal_collect(
        lambda ss: ss.read_parquet(path).filter(col("i") > lit(-50)),
        s, ignore_order=True)


def test_avro_roundtrip(session, tmp_path):
    """Avro OCF read (reference GpuAvroScan/AvroDataFileReader): both
    codecs, nullable primitives, date/timestamp logical types."""
    import datetime
    from spark_rapids_tpu.io.avro import read_avro, write_avro
    t = pa.table({
        "i": pa.array([1, None, 3], pa.int32()),
        "l": pa.array([10, 20, None], pa.int64()),
        "f": pa.array([1.5, None, -2.5], pa.float64()),
        "s": pa.array(["a", "bb", None]),
        "b": pa.array([True, None, False]),
        "d": pa.array([datetime.date(2020, 1, 2), None,
                       datetime.date(1999, 12, 31)], pa.date32()),
        "ts": pa.array([datetime.datetime(2020, 1, 2, 3, 4, 5), None,
                        datetime.datetime(1970, 1, 1)], pa.timestamp("us")),
    })
    for codec in ("null", "deflate"):
        path = str(tmp_path / f"t_{codec}.avro")
        write_avro(path, t, codec=codec)
        back = read_avro(path)
        assert back.to_pylist() == t.to_pylist()
        # engine scan path: differential vs CPU backend
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: s.read_avro(path).filter(col("l") > lit(5)),
            session, ignore_order=True)


def test_avro_aggregate(session, tmp_path):
    from spark_rapids_tpu.io.avro import write_avro
    t = pa.table({"k": pa.array(["x", "y", "x", "x"]),
                  "v": pa.array([1, 2, 3, 4], pa.int64())})
    path = str(tmp_path / "agg.avro")
    write_avro(path, t, codec="deflate")
    from spark_rapids_tpu.sql import functions as F
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.read_avro(path).group_by(col("k")).agg(
            F.sum("v").alias("sv")),
        session, ignore_order=True)


def test_max_records_per_file_and_write_stats(session, tmp_path):
    # reference GpuFileFormatDataWriter maxRecordsPerFile +
    # BasicColumnarWriteJobStatsTracker
    import os
    t = pa.table({"k": pa.array((np.arange(100) % 4).astype(np.int64)),
                  "v": pa.array(np.arange(100).astype(np.float64))})
    df = session.create_dataframe(t)
    w = df.write.mode("overwrite").option("maxRecordsPerFile", 30)
    p = str(tmp_path / "out")
    w.parquet(p)
    files = [f for f in os.listdir(p) if f.endswith(".parquet")]
    assert len(files) == 4  # 100 rows / 30 -> 4 part files
    st = w.last_write_stats
    assert st["numFiles"] == 4
    assert st["numOutputRows"] == 100
    assert st["numOutputBytes"] > 0
    import pyarrow.parquet as _pq
    total = sum(_pq.ParquetFile(os.path.join(p, f)).metadata.num_rows
                for f in files)
    assert total == 100

    # partitioned write: stats count partition dirs
    w2 = df.write.mode("overwrite").partition_by("k")
    p2 = str(tmp_path / "out2")
    w2.parquet(p2)
    assert w2.last_write_stats["numParts"] == 4
    assert w2.last_write_stats["numOutputRows"] == 100
