"""Broadcast nested-loop join differential tests (reference
GpuBroadcastNestedLoopJoinExecBase: non-equi conditions, all join kinds)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect
from data_gen import IntegerGen, LongGen, DoubleGen, gen_df


@pytest.fixture
def session():
    return TpuSession()


def _l(s, parts=1):
    return s.create_dataframe({
        "a": pa.array([1, 2, 3, 4, None], pa.int64()),
        "lv": pa.array([10.0, 20.0, 30.0, 40.0, 50.0]),
    }, num_partitions=parts)


def _r(s):
    return s.create_dataframe({
        "b": pa.array([2, 3, 5, None], pa.int64()),
        "rv": pa.array([200.0, 300.0, 500.0, 600.0]),
    })


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_bnlj_range_condition(session, how):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _l(s).join(_r(s), on=col("a") < col("b"), how=how),
        session, ignore_order=True)


def test_bnlj_compound_condition(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _l(s).join(
            _r(s), on=(col("a") < col("b")) & (col("rv") > col("lv") * lit(5.0)),
            how="inner"),
        session, ignore_order=True)


def test_bnlj_multi_partition_probe(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: _l(s, parts=3).join(_r(s), on=col("a") >= col("b"),
                                      how="left"),
        session, ignore_order=True)


def test_bnlj_empty_build(session):
    empty = TpuSession().create_dataframe(
        {"b": pa.array([], pa.int64()), "rv": pa.array([], pa.float64())})

    def q(s):
        e = s.create_dataframe({"b": pa.array([], pa.int64()),
                                "rv": pa.array([], pa.float64())})
        return _l(s).join(e, on=col("a") < col("b"), how="left")
    assert_tpu_and_cpu_are_equal_collect(q, session, ignore_order=True)


def test_bnlj_generated(session):
    lspec = [("a", IntegerGen(min_val=0, max_val=60)), ("lv", LongGen())]
    rspec = [("b", IntegerGen(min_val=30, max_val=90)),
             ("rv", DoubleGen(no_nans=True))]
    for how in ["inner", "left", "left_semi", "left_anti"]:
        assert_tpu_and_cpu_are_equal_collect(
            lambda s: gen_df(s, lspec, length=300, seed=97)
            .join(gen_df(s, rspec, length=200, seed=101),
                  on=(col("a") > col("b") - lit(5))
                  & (col("a") < col("b") + lit(5)), how=how),
            session, ignore_order=True)
