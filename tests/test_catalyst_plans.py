"""Golden Catalyst physical-plan corpus, driven end-to-end: the real
Spark `executedPlan.toJSON` wire format (preorder TreeNode arrays,
Partial/Final aggregate pairs, exchanges, AQE wrappers) through
plan/catalyst.py -> planner -> execution -> Arrow, differentially
asserted against pyarrow/pandas-computed expectations (VERDICT r4 #2;
reference Plugin.scala:53-60 / GpuOverrides.scala:4744)."""
import json
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.plan.catalyst import ingest_catalyst
from spark_rapids_tpu.sql.session import TpuSession

GOLDEN = os.path.join(os.path.dirname(__file__), "golden_plans")


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    data = tmp_path_factory.mktemp("catalyst_data")
    rng = np.random.default_rng(31)
    n = 4000
    li = pa.table({
        "l_orderkey": rng.integers(0, 300, n),
        "l_quantity": np.round(rng.uniform(1, 100, n), 2),
        "l_extendedprice": np.round(rng.uniform(1, 1000, n), 2),
        "l_discount": np.round(rng.uniform(0, 0.1, n), 3),
        "l_shipdate": rng.integers(0, 200, n).astype(np.int32),
        "l_flag": np.array(["A", "B", "C"])[rng.integers(0, 3, n)],
    })
    od = pa.table({
        "o_orderkey": np.arange(300, dtype=np.int64),
        "o_orderdate": rng.integers(0, 200, 300).astype(np.int32),
        "o_prio": np.array(["HIGH", "LOW"])[rng.integers(0, 2, 300)],
    })
    pq.write_table(li, str(data / "lineitem.parquet"))
    pq.write_table(od, str(data / "orders.parquet"))
    return TpuSession(), str(data), li.to_pandas(), od.to_pandas()


def run(env, name):
    sess, data, li, od = env
    with open(os.path.join(GOLDEN, name + ".json")) as f:
        raw = f.read().replace("$DATA", data)
    df = ingest_catalyst(raw, sess)
    return df, li, od


def test_q6_filter_agg(env):
    df, li, od = run(env, "q6_filter_agg")
    got = df.collect().to_pylist()[0]["revenue"]
    m = li[(li.l_shipdate >= 100) & (li.l_quantity < 24.0)]
    want = float((m.l_extendedprice * m.l_discount).sum())
    assert got == pytest.approx(want, rel=1e-9)


def test_project_filter(env):
    df, li, od = run(env, "project_filter")
    got = df.collect()
    assert got.schema.names == ["l_orderkey", "qplus"]
    assert got.num_rows == len(li)
    assert sorted(got["qplus"].to_pylist())[0] == pytest.approx(
        float(li.l_quantity.min()) + 1.0)


def test_q3_join_agg_topn(env):
    df, li, od = run(env, "q3_join_agg_topn")
    got = df.collect().to_pylist()
    m = li[li.l_shipdate > 50].merge(
        od[od.o_orderdate < 150], left_on="l_orderkey",
        right_on="o_orderkey")
    g = (m.groupby("l_orderkey")["l_extendedprice"].sum()
         .reset_index().sort_values(["l_extendedprice", "l_orderkey"],
                                    ascending=[False, True]).head(10))
    want = [{"l_orderkey": int(r.l_orderkey),
             "rev": pytest.approx(float(r.l_extendedprice), rel=1e-9)}
            for r in g.itertuples()]
    assert got == want


def test_sort_limit(env):
    df, li, od = run(env, "sort_limit")
    got = [r["l_extendedprice"] for r in df.collect().to_pylist()]
    want = sorted(li.l_extendedprice, reverse=True)[:5]
    assert got == pytest.approx(want)


def test_union_filters(env):
    df, li, od = run(env, "union_filters")
    got = df.collect()
    want = int((li.l_quantity < 5.0).sum() + (li.l_quantity > 95.0).sum())
    assert got.num_rows == want


def test_semi_join(env):
    df, li, od = run(env, "semi_join")
    got = df.collect()
    high = set(od[od.o_prio == "HIGH"].o_orderkey)
    assert got.num_rows == int(li.l_orderkey.isin(high).sum())
    assert got.schema.names == [c for c in li.columns]


def test_bhj_condition(env):
    df, li, od = run(env, "bhj_condition")
    got = df.collect()
    m = li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    want = int((m.l_shipdate > m.o_orderdate).sum())
    assert got.num_rows == want


def test_expand_rollup_agg(env):
    df, li, od = run(env, "expand_rollup_agg")
    got = {(r["flag_e"], r["spark_grouping_id"]):
           round(r["sum_qty"], 6) for r in df.collect().to_pylist()}
    want = {(k, 0): round(float(v), 6)
            for k, v in li.groupby("l_flag")["l_quantity"].sum().items()}
    want[(None, 1)] = round(float(li.l_quantity.sum()), 6)
    assert got == want


def test_expr_breadth(env):
    df, li, od = run(env, "expr_breadth")
    got = df.collect().to_pylist()
    assert df.collect().schema.names == ["bucket", "in3", "f1", "isa",
                                         "qlong"]
    for r, (_, src) in zip(got, li.iterrows()):
        assert r["bucket"] == ("low" if src.l_quantity < 10.0 else "high")
        assert r["in3"] == (src.l_shipdate in (1, 2, 3))
        assert r["f1"] == src.l_flag[0]
        assert r["isa"] == src.l_flag.startswith("A")
        assert r["qlong"] == int(src.l_quantity)


def test_count_star(env):
    df, li, od = run(env, "count_star")
    assert df.collect().to_pylist() == [{"count(1)": len(li)}]


def test_multi_agg(env):
    df, li, od = run(env, "multi_agg")
    got = {r["l_flag"]: r for r in df.collect().to_pylist()}
    g = li.groupby("l_flag")
    for flag, grp in g:
        assert got[flag]["avg_q"] == pytest.approx(
            float(grp.l_quantity.mean()), rel=1e-9)
        assert got[flag]["min_p"] == pytest.approx(
            float(grp.l_extendedprice.min()))
        assert got[flag]["max_d"] == pytest.approx(
            float(grp.l_discount.max()))


def test_anti_join_aqe(env):
    df, li, od = run(env, "anti_join_aqe")
    got = df.collect()
    want = int((~li.l_orderkey.isin(set(od.o_orderkey))).sum())
    assert got.num_rows == want


def test_unsupported_class_rejects(env):
    sess, data, li, od = env
    from spark_rapids_tpu.expr.core import SparkException
    bad = [{"class": "org.apache.spark.sql.execution.python."
            "ArrowEvalPythonExec", "num-children": 0}]
    with pytest.raises(SparkException, match="ArrowEvalPythonExec"):
        ingest_catalyst(json.dumps(bad), sess)
