"""Regression tests for the round-2 advisor findings (ADVICE.md)."""
import decimal

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit


def test_percentile_decimal_descaled():
    t = pa.table({
        "k": pa.array([1, 1, 1], type=pa.int64()),
        "d": pa.array([decimal.Decimal("1.50"), decimal.Decimal("2.50"),
                       decimal.Decimal("3.50")], type=pa.decimal128(10, 2)),
    })
    s = TpuSession()
    g = (s.create_dataframe(t).group_by(col("k"))
         .agg(F.percentile(col("d"), 0.5).alias("p")))
    d = g.to_pydict()
    assert abs(d["p"][0] - 2.5) < 1e-9, d


def test_decimal_times_big_long_is_double():
    """decimal x long with overflow potential computes as DOUBLE instead
    of wrapping int64 (ADVICE medium #2)."""
    t = pa.table({
        "d": pa.array([decimal.Decimal("100.00")], type=pa.decimal128(10, 2)),
        "n": pa.array([10**15], type=pa.int64()),
    })
    s = TpuSession()
    out = s.create_dataframe(t).select((col("d") * col("n")).alias("x"))
    d = out.to_pydict()
    assert abs(d["x"][0] - 1e17) <= 1e8  # double result, no wrap / no crash


def test_collect_list_decimal_cpu_tier():
    t = pa.table({
        "k": pa.array([1, 1, 2], type=pa.int64()),
        "d": pa.array([decimal.Decimal("1.25"), decimal.Decimal("2.75"),
                       decimal.Decimal("-3.50")], type=pa.decimal128(9, 2)),
    })
    s = TpuSession()
    g = (s.create_dataframe(t).group_by(col("k"))
         .agg(F.collect_list(col("d")).alias("l"),
              F.collect_set(col("d")).alias("st")))
    d = g.to_pydict()
    got = dict(zip(d["k"], d["l"]))
    assert sorted(got[1]) == [decimal.Decimal("1.25"), decimal.Decimal("2.75")]
    assert got[2] == [decimal.Decimal("-3.50")]


def test_get_json_object_single_wildcard_unwraps():
    t = pa.table({"j": pa.array(['{"a":[{"b":1}]}', '{"a":[{"b":1},{"b":2}]}'])})
    s = TpuSession()
    out = s.create_dataframe(t).select(
        F.get_json_object(col("j"), "$.a[*].b").alias("x"))
    d = out.to_pydict()
    assert d["x"] == ["1", "[1,2]"]
