"""Test environment: force the XLA CPU backend with 8 virtual devices BEFORE
jax loads, so the full suite (including multi-chip sharding tests) runs
without TPU hardware -- the host-simulator capability SURVEY.md §4.4 notes
the reference lacks (its CI needs real GPUs)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# XLA:CPU fast-math rewrites f64 division into reciprocal-multiply (1 ulp
# off); the TPU backend is unaffected, but differential tests on the CPU
# simulator need exact IEEE semantics.
if "xla_cpu_enable_fast_math" not in flags:
    flags = (flags + " --xla_cpu_enable_fast_math=false").strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

# The hosting environment's site customization pins jax_platforms to its TPU
# plugin regardless of JAX_PLATFORMS; override it explicitly for the suite.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: full NDS-scale runs excluded from tier-1 (-m 'not slow')")


@pytest.fixture(autouse=True)
def _reset_runtime():
    yield
    from spark_rapids_tpu.runtime import faults, watchdog
    from spark_rapids_tpu.runtime.semaphore import reset_semaphore
    from spark_rapids_tpu.runtime.memory import reset_spill_framework
    from spark_rapids_tpu.runtime.retry import OomInjector, set_backoff
    reset_semaphore()
    reset_spill_framework()
    OomInjector.configure(0)
    faults.configure("")
    set_backoff(10.0, 500.0)
    # a test that tripped the breaker (or started the watchdog) must not
    # leak degraded routing into the next test's queries
    watchdog.uninstall_for_tests()
    # flight rings / dump rate-limit state, the per-query attribution
    # aggregate, and SLO baselines are process-global too
    from spark_rapids_tpu.runtime import obs
    from spark_rapids_tpu.runtime.obs import (attribution, flight, live,
                                              reqtrace)
    flight.uninstall_for_tests()
    # the per-request recorder (and this thread's request binding) is
    # process-global the same way the flight recorder is
    reqtrace.uninstall_for_tests()
    attribution.reset_for_tests()
    # the live query registry and this thread's query-id binding are
    # process-global (the sampler's one daemon thread deliberately
    # persists — it is process-global by design and reads only peeks)
    live.reset_for_tests()
    st = obs.state()
    if st is not None:
        if st.slo is not None:
            st.slo.reset_for_tests()
        st.last_slow = None
        st.last_roofline = None
    # the kernel cost auditor: disarm + drop the per-query tally and
    # findings; the (entry, shape) record table deliberately persists —
    # it mirrors the process-wide warm-trace cache (tests wanting a
    # cold audit call kernel_audit.clear_for_cold_audit())
    from spark_rapids_tpu.analysis import kernel_audit
    kernel_audit.reset_for_tests()
    # a test that armed AOT warmup must not leak its manager (and its
    # captured session) into the next test; the warm-trace cache itself
    # deliberately persists — it is process-global by design and tests
    # asserting compile counts diff the stats around their own queries
    from spark_rapids_tpu.runtime import shapes, warmup
    warmup.reset_for_tests()
    shapes.configure(2.0, True)
    # query lifecycle control: cancel tokens, the admission gate, the
    # deadline sweeper and reject/cancel counters are process-global —
    # a cancelled or queued query must not leak into the next test
    from spark_rapids_tpu.runtime import lifecycle
    lifecycle.reset_for_tests()
    # the serving layer installs a process-global query server (and its
    # result cache) on the first serving-enabled session; drop it so one
    # test's server, sessions and cached results don't leak forward
    from spark_rapids_tpu.runtime import serving
    serving.reset_for_tests()
    # adaptive execution: the decision recorder, build-reuse cache and
    # table epoch are process-global, as is the measured-hints memo —
    # one test's cached broadcast build or hint must not leak forward
    from spark_rapids_tpu.exec import adaptive
    adaptive.reset_for_tests()
    from spark_rapids_tpu.plan import cost
    cost.reset_for_tests()
