"""Bytecode UDF compiler tests (reference udf-compiler/OpcodeSuite):
supported bodies plan as fused device expressions; unsupported ones fall
back to the row tier; both produce identical results."""
import math

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql.udf import PythonRowUDF, udf
from spark_rapids_tpu.sql.udf_compiler import compile_udf
from spark_rapids_tpu.expr.core import BoundRef, col

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture(autouse=True)
def _enable_compiler():
    # The compiler is off by default (matching the reference conf); these
    # tests exercise it, so turn it on for the module.
    from spark_rapids_tpu import config as C
    old = C.conf().get(C.UDF_COMPILER_ENABLED)
    C.conf().set(C.UDF_COMPILER_ENABLED.key, "true")
    yield
    C.conf().set(C.UDF_COMPILER_ENABLED.key, str(old).lower())


@pytest.fixture
def session():
    # session-level conf too: _activate() republishes the session conf on
    # every dataframe op, which would otherwise mask the global set above
    return TpuSession(
        conf_overrides={"spark.rapids.sql.udfCompiler.enabled": "true"})


def _refs(*dts):
    return [BoundRef(i, dt, f"c{i}") for i, dt in enumerate(dts)]


def test_compiles_arithmetic_and_ternary():
    assert compile_udf(lambda x: x * 2 + 1, _refs(T.INT64)) is not None
    assert compile_udf(lambda x, y: (x - y) / (x + y + 1),
                       _refs(T.FLOAT64, T.FLOAT64)) is not None
    assert compile_udf(lambda x: x if x > 0 else -x,
                       _refs(T.INT64)) is not None
    assert compile_udf(lambda x: abs(x) + max(x, 0) + min(x, 10),
                       _refs(T.INT64)) is not None
    assert compile_udf(lambda x: math.sqrt(x) + math.log(x + 1.0),
                       _refs(T.FLOAT64)) is not None

    def straight_line(a, b):
        s = a + b
        d = a - b
        return s * d

    assert compile_udf(straight_line, _refs(T.INT64, T.INT64)) is not None


def test_rejects_outside_subset():
    # loops
    def loop(x):
        t = 0
        for i in range(3):
            t += x
        return t
    assert compile_udf(loop, _refs(T.INT64)) is None
    # unknown calls
    assert compile_udf(lambda x: hash(x), _refs(T.INT64)) is None
    # data structures
    assert compile_udf(lambda x: [x, x], _refs(T.INT64)) is None


def test_udf_plans_as_device_expression(session):
    f = udf(lambda x: x * 3 + 1, return_type=T.INT64)
    e = f(col("a"))
    assert not isinstance(e, PythonRowUDF), "should compile to expressions"
    t = pa.table({"a": pa.array([1, 2, None, -5], pa.int64())})
    out = session.create_dataframe(t).select(e.alias("r")).to_pydict()
    assert out["r"] == [4, 7, None, -14]
    # the plan must NOT contain a CPU fallback
    txt = session.create_dataframe(t).select(e.alias("r")).explain()
    assert "cannot run on TPU" not in txt


@pytest.mark.parametrize("fn,dt", [
    (lambda x: x * x - 2 * x + 7, T.INT64),
    (lambda x: x if x % 2 == 0 else 3 * x + 1, T.INT64),
    (lambda x: abs(x) ** 0.5 if x > 0 else 0.0, T.FLOAT64),
    (lambda x: math.floor(x / 3.0) + math.ceil(x / 7.0), T.FLOAT64),
])
def test_compiled_matches_row_tier(session, fn, dt):
    rng = np.random.default_rng(11)
    vals = rng.integers(-100, 100, 50).astype(np.int64)
    t = pa.table({"a": pa.array(vals)})
    compiled = udf(fn, return_type=T.FLOAT64)(col("a"))
    assert not isinstance(compiled, PythonRowUDF)
    row = PythonRowUDF(fn, T.FLOAT64, [col("a")])
    got = session.create_dataframe(t).select(
        compiled.alias("c")).to_pydict()["c"]
    exp = session.create_dataframe(t).select(
        row.alias("c")).to_pydict()["c"]
    assert len(got) == len(exp)
    for g, e in zip(got, exp):
        assert (g is None) == (e is None)
        if g is not None:
            assert abs(g - e) <= 1e-9 * max(1.0, abs(e)), (g, e)


def test_conf_disables_compiler(session):
    from spark_rapids_tpu import config as C
    old = C.conf().get(C.UDF_COMPILER_ENABLED)
    try:
        C.conf().set(C.UDF_COMPILER_ENABLED.key, "false")
        e = udf(lambda x: x + 1, return_type=T.INT64)(col("a"))
        assert isinstance(e, PythonRowUDF)
    finally:
        C.conf().set(C.UDF_COMPILER_ENABLED.key, str(old).lower())


def test_string_len_and_closure_consts(session):
    k = 10

    def shifted(x):
        return x + k

    e = compile_udf(shifted, _refs(T.INT64))
    assert e is not None
    t = pa.table({"a": pa.array([1, 2], pa.int64()),
                  "s": pa.array(["ab", "héllo"])})
    f = udf(lambda s: len(s), return_type=T.INT32)
    es = f(col("s"))
    assert not isinstance(es, PythonRowUDF)
    out = session.create_dataframe(t).select(es.alias("n")).to_pydict()
    assert out["n"] == [2, 5]


def test_python_mod_floordiv_semantics(session):
    # Python % takes the divisor's sign; // floors — both differ from
    # Spark's Remainder/IntegralDivide for negative operands
    t = pa.table({"a": pa.array([-7, 7, -7, 7, 0, -1], pa.int64()),
                  "b": pa.array([3, 3, -3, -3, 3, 2], pa.int64())})
    fmod = udf(lambda x, y: x % y, return_type=T.INT64)
    fdiv = udf(lambda x, y: x // y, return_type=T.INT64)
    em, ed = fmod(col("a"), col("b")), fdiv(col("a"), col("b"))
    assert not isinstance(em, PythonRowUDF)
    out = session.create_dataframe(t).select(
        em.alias("m"), ed.alias("d")).to_pydict()
    av = [-7, 7, -7, 7, 0, -1]
    bv = [3, 3, -3, -3, 3, 2]
    assert out["m"] == [x % y for x, y in zip(av, bv)]
    assert out["d"] == [x // y for x, y in zip(av, bv)]
