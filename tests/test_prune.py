"""Column pruning pass: Project-over-Join/Window pushes used columns
below the operator (plan/prune.py); results stay identical."""
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit


def test_join_prune_plan_shape_and_result():
    s = TpuSession()
    left = s.create_dataframe({"k": [1, 2, 3, 4], "a": [10, 20, 30, 40],
                               "b": [1.0, 2.0, 3.0, 4.0],
                               "unused1": [0, 0, 0, 0]})
    right = s.create_dataframe({"rk": [2, 3, 5], "c": [200, 300, 500],
                                "unused2": [9, 9, 9]})
    j = left.join(right, on=[(col("k"), col("rk"))], how="inner")
    out = j.select(col("k"), col("c"))
    from spark_rapids_tpu.plan.prune import prune_plan
    import spark_rapids_tpu.plan.nodes as P
    pruned = prune_plan(out.plan)
    # the join's children should now carry only the used subsets
    join_node = pruned.children[0]
    assert isinstance(join_node, P.Join)
    assert join_node.children[0].schema.names == ["k"]
    assert set(join_node.children[1].schema.names) == {"rk", "c"}
    d = out.to_pydict()
    assert sorted(zip(d["k"], d["c"])) == [(2, 200), (3, 300)]


def test_join_prune_with_condition_result():
    s = TpuSession()
    left = s.create_dataframe({"k": [1, 1, 2], "x": [5, 6, 7],
                               "dead": [0, 0, 0]})
    right = s.create_dataframe({"rk": [1, 2], "y": [5, 9],
                                "dead2": [1, 1]})
    j = left.join(right, on=(col("k") == col("rk")) & (col("x") > col("y")),
                  how="inner")
    out = j.select(col("k"), col("x"), col("y"))
    d = out.to_pydict()
    rows = sorted(zip(d["k"], d["x"], d["y"]))
    assert rows == [(1, 6, 5)]


def test_window_prune_plan_shape_and_result():
    s = TpuSession()
    from spark_rapids_tpu.expr.window import Window
    t = pa.table({
        "g": pa.array([1, 1, 2, 2, 2], type=pa.int64()),
        "o": pa.array([3, 1, 2, 5, 4], type=pa.int64()),
        "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0]),
        "unused": pa.array([0, 0, 0, 0, 0], type=pa.int64()),
    })
    df = s.create_dataframe(t)
    w = Window.partition_by(col("g")).order_by(col("o"))
    out = df.select(col("g"), F.rank().over(w).alias("rk"))
    from spark_rapids_tpu.plan.prune import prune_plan
    import spark_rapids_tpu.plan.nodes as P
    pruned = prune_plan(out.plan)
    wn = pruned.children[0]
    assert isinstance(wn, P.WindowNode)
    assert set(wn.children[0].schema.names) == {"g", "o"}
    d = out.to_pydict()
    got = sorted(zip(d["g"], d["rk"]))
    assert got == [(1, 1), (1, 2), (2, 1), (2, 2), (2, 3)]
