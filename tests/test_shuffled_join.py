"""Shuffled hash join + skew sub-partitioning differential tests
(reference GpuShuffledHashJoinExec / GpuSubPartitionHashJoin)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect

SHUFFLE_CONF = {"spark.rapids.sql.join.broadcastRowThreshold": 1}
SUBPART_CONF = {"spark.rapids.sql.join.broadcastRowThreshold": 1,
                "spark.rapids.sql.join.subPartitionRows": 8}


def _sides(n=60, seed=5):
    rng = np.random.default_rng(seed)
    left = pa.table({
        "k": pa.array([None if rng.random() < 0.1 else int(x)
                       for x in rng.integers(0, 12, n)], pa.int64()),
        "lv": pa.array(rng.integers(0, 100, n).astype(np.int64)),
    })
    right = pa.table({
        "k": pa.array([None if rng.random() < 0.1 else int(x)
                       for x in rng.integers(0, 15, n // 2)], pa.int64()),
        "rv": pa.array(rng.uniform(0, 1, n // 2)),
    })
    return left, right


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_shuffled_join_all_kinds(how):
    left_t, right_t = _sides()
    session = TpuSession(SHUFFLE_CONF)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(left_t, num_partitions=3)
        .join(s.create_dataframe(right_t, num_partitions=2), on="k", how=how),
        session, ignore_order=True)


@pytest.mark.parametrize("how", ["inner", "left"])
def test_subpartitioned_join_skewed(how):
    # heavily skewed: key 0 dominates; tiny subPartitionRows forces the
    # hash-bucket pairwise join path
    rng = np.random.default_rng(9)
    left_t = pa.table({"k": pa.array(np.where(rng.random(80) < 0.7, 0,
                                              rng.integers(0, 6, 80)).astype(np.int64)),
                       "lv": pa.array(np.arange(80, dtype=np.int64))})
    right_t = pa.table({"k": pa.array(rng.integers(0, 6, 40).astype(np.int64)),
                        "rv": pa.array(np.arange(40, dtype=np.int64))})
    session = TpuSession(SUBPART_CONF)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(left_t)
        .join(s.create_dataframe(right_t), on="k", how=how),
        session, ignore_order=True)


def test_shuffled_join_string_keys():
    rng = np.random.default_rng(2)
    left_t = pa.table({"k": pa.array(np.array(["a", "b", "c", "d"], object)[
        rng.integers(0, 4, 50)]), "lv": pa.array(np.arange(50, dtype=np.int64))})
    right_t = pa.table({"k": ["a", "c", "e"], "rv": [1.0, 2.0, 3.0]})
    session = TpuSession(SHUFFLE_CONF)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(left_t, num_partitions=2)
        .join(s.create_dataframe(right_t, num_partitions=2), on="k", how="inner"),
        session, ignore_order=True)


def test_shuffled_join_with_condition():
    left_t, right_t = _sides(40)
    session = TpuSession(SHUFFLE_CONF)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(left_t, num_partitions=2)
        .join(s.create_dataframe(right_t, num_partitions=2), on="k", how="inner")
        .filter(col("lv") > lit(20)),
        session, ignore_order=True)


def test_out_of_core_sort():
    # tiny threshold forces the host-staged out-of-core sort path
    import pyarrow as pa
    rng = np.random.default_rng(4)
    t = pa.table({"k": pa.array(rng.integers(0, 1000, 500).astype(np.int64)),
                  "s": pa.array(np.array(["aa", "bb", "cc"], object)[
                      rng.integers(0, 3, 500)]),
                  "v": pa.array(rng.uniform(-5, 5, 500))})
    session = TpuSession({"spark.rapids.sql.sort.outOfCoreBytes": 1024,
                          "spark.rapids.sql.reader.batchSizeRows": 64})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).order_by(col("k"), col("v")),
        session)


def test_out_of_core_sort_descending_nulls():
    import pyarrow as pa
    from spark_rapids_tpu.plan.nodes import SortOrder
    rng = np.random.default_rng(6)
    t = pa.table({"k": pa.array([None if rng.random() < 0.2 else int(x)
                                 for x in rng.integers(0, 50, 300)], pa.int64())})
    session = TpuSession({"spark.rapids.sql.sort.outOfCoreBytes": 256,
                          "spark.rapids.sql.reader.batchSizeRows": 50})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).order_by(
            SortOrder(col("k"), ascending=False, nulls_first=False)),
        session)
