"""Window function differential tests (reference window_function_test.py
style — CPU vs TPU result diff per function/frame)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.window import Window

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


def _t(n=60, seed=11):
    rng = np.random.default_rng(seed)
    return pa.table({
        "g": pa.array(np.array(["x", "y", "z"], object)[rng.integers(0, 3, n)]),
        "o": pa.array(rng.integers(0, 10, n).astype(np.int64)),
        "v": pa.array([None if rng.random() < 0.2 else float(x)
                       for x in rng.integers(-5, 20, n)]),
    })


W_GO = Window.partition_by("g").order_by("o")


def test_row_number_rank_dense_rank(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), col("o"),
            F.row_number().over(W_GO).alias("rn"),
            F.rank().over(W_GO).alias("rk"),
            F.dense_rank().over(W_GO).alias("dr")),
        session, ignore_order=True)


def test_ntile(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), col("o"), F.ntile(4).over(W_GO).alias("nt")),
        session, ignore_order=True)


def test_lead_lag(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), col("o"), col("v"),
            F.lead(col("v")).over(W_GO).alias("ld"),
            F.lag(col("v"), 2).over(W_GO).alias("lg"),
            F.lead(col("o"), 1, -1).over(W_GO).alias("ld_def")),
        session, ignore_order=True)


def test_running_aggs_default_range_frame(session):
    # default frame with ORDER BY = RANGE UNBOUNDED PRECEDING..CURRENT ROW
    # (includes peer rows — the tie semantics)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), col("o"), col("v"),
            F.sum(col("v")).over(W_GO).alias("rsum"),
            F.count(col("v")).over(W_GO).alias("rcnt"),
            F.min(col("v")).over(W_GO).alias("rmin"),
            F.max(col("v")).over(W_GO).alias("rmax"),
            F.avg(col("v")).over(W_GO).alias("ravg")),
        session, ignore_order=True, approx_float=1e-9)


def test_whole_partition_frame(session):
    w = Window.partition_by("g")
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), col("v"),
            F.sum(col("v")).over(w).alias("psum"),
            F.count("*").over(w).alias("pcnt")),
        session, ignore_order=True, approx_float=1e-9)


def test_bounded_rows_frame(session):
    w = W_GO.rows_between(-2, 1)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), col("o"), col("v"),
            F.sum(col("v")).over(w).alias("bsum"),
            F.count(col("v")).over(w).alias("bcnt"),
            F.avg(col("v")).over(w).alias("bavg")),
        session, ignore_order=True, approx_float=1e-9)


def test_window_no_partition(session):
    w = Window.order_by("o")
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t(30)).select(
            col("o"), F.row_number().over(w).alias("rn"),
            F.sum(col("v")).over(w).alias("rs")),
        session, ignore_order=True, approx_float=1e-9)


def test_window_multi_partition_input(session):
    # forces a hash exchange on the partition keys below the window
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t(80), num_partitions=3).select(
            col("g"), col("o"),
            F.row_number().over(W_GO).alias("rn"),
            F.sum(col("v")).over(W_GO).alias("rs")),
        session, ignore_order=True, approx_float=1e-9)


def test_window_over_filtered_masked_input(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).filter(col("o") > lit(2)).select(
            col("g"), col("o"),
            F.row_number().over(W_GO).alias("rn")),
        session, ignore_order=True)


def test_window_expr_arithmetic(session):
    # window expr nested inside arithmetic in the projection
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), (F.row_number().over(W_GO) * lit(10)).alias("rn10")),
        session, ignore_order=True)


def test_unsupported_window_falls_back(session):
    # stddev in a window frame -> whole node falls back to CPU, results equal
    from asserts import assert_fallback_collect
    assert_fallback_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), F.stddev(col("v")).over(W_GO).alias("sd")),
        session, "WindowNode", ignore_order=True)


# -- window breadth: percent_rank / cume_dist / nth_value / first/last ------

def test_percent_rank_cume_dist(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), col("o"),
            F.percent_rank().over(W_GO).alias("pr"),
            F.cume_dist().over(W_GO).alias("cd")),
        session, ignore_order=True, approx_float=1e-12)


def test_nth_first_last_value(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_t()).select(
            col("g"), col("o"),
            F.first_value(col("v")).over(W_GO).alias("fv"),
            F.last_value(col("v")).over(W_GO).alias("lv"),
            F.nth_value(col("v"), 2).over(W_GO).alias("n2")),
        session, ignore_order=True)


def test_window_breadth_generated(session):
    from data_gen import IntegerGen, LongGen, UniqueLongGen, RepeatSeqGen, gen_df
    spec = [("p", RepeatSeqGen(IntegerGen(min_val=0, max_val=12), length=10)),
            ("o", UniqueLongGen()),
            ("v", LongGen(min_val=-1000, max_val=1000))]
    w = Window.partition_by(col("p")).order_by(col("o"))
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=1024, seed=103).select(
            col("p"), col("o"),
            F.percent_rank().over(w).alias("pr"),
            F.cume_dist().over(w).alias("cd"),
            F.nth_value(col("v"), 3).over(w).alias("n3"),
            F.first_value(col("v")).over(w).alias("fv"),
            F.last_value(col("v")).over(w).alias("lv")),
        session, ignore_order=True, approx_float=1e-12)
