"""analysis/ package regression: tpulint rules on seeded sources (and a
clean full tree), the runtime concurrency sanitizer on seeded lock
inversions / held-lock I/O (and silence on the clean engine under a full
NDS-probe query), and the plan-invariant verifier against the golden
dispatch budgets."""
import importlib.util
import json
import os
import textwrap
import threading
import time

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
GOLDEN = os.path.join(os.path.dirname(__file__), "golden_plans",
                      "dispatch_budgets.json")

_spec = importlib.util.spec_from_file_location(
    "nds_probe", os.path.join(REPO, "tools", "nds_probe.py"))
nds = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(nds)

from spark_rapids_tpu.analysis import lint, sanitizer  # noqa: E402
from spark_rapids_tpu.analysis.plan_verify import (  # noqa: E402
    PlanVerifyError, check_plan, compare_budget, dispatch_budget,
    verify_plan)
from spark_rapids_tpu.sql.session import TpuSession  # noqa: E402


# ---------------------------------------------------------------------------
# tpulint: each rule on a seeded source fragment
# ---------------------------------------------------------------------------

def _lint(src, relpath="runtime/x.py", known=frozenset({"opTime"})):
    return lint.lint_source(textwrap.dedent(src), "/x/" + relpath,
                            set(known), relpath=relpath)


def _rules(violations, suppressed=False):
    return [v.rule for v in violations if v.suppressed == suppressed]


def test_l001_logging_under_lock():
    vs = _lint("""
        import logging
        log = logging.getLogger(__name__)
        class X:
            def f(self):
                with self._lock:
                    log.info("inside the critical section")
    """)
    assert _rules(vs) == ["TPU-L001"]


def test_l001_io_and_blocking_under_lock():
    vs = _lint("""
        class X:
            def f(self, fut):
                with self._lock:
                    np.save(self.path, self.arr)
                    fut.result()
    """)
    assert _rules(vs) == ["TPU-L001", "TPU-L001"]


def test_l001_trace_emission_under_lock():
    vs = _lint("""
        from spark_rapids_tpu.runtime import trace
        class X:
            def f(self):
                with self._cv:
                    trace.instant("stall")
    """)
    assert _rules(vs) == ["TPU-L001"]


def test_l001_cv_wait_on_itself_is_protocol_not_violation():
    vs = _lint("""
        class X:
            def f(self):
                with self._cv:
                    self._cv.wait(1.0)
    """)
    assert _rules(vs) == []


def test_l001_nested_def_does_not_run_under_lock():
    vs = _lint("""
        class X:
            def f(self):
                with self._lock:
                    def emit():
                        print("runs later, outside the lock")
                    self.pending = emit
    """)
    assert _rules(vs) == []


def test_l001_suppression_on_with_line():
    vs = _lint("""
        class X:
            def f(self):
                with self._lock:  # tpulint: disable=TPU-L001 atomic-with-tier-transition
                    np.save(self.path, self.arr)
    """)
    assert _rules(vs) == []
    sup = [v for v in vs if v.suppressed]
    assert len(sup) == 1 and sup[0].reason


def test_l002_bare_executor_and_thread():
    vs = _lint("""
        from concurrent.futures import ThreadPoolExecutor
        import threading
        pool = ThreadPoolExecutor(4)
        t = threading.Thread(target=print)
    """)
    assert _rules(vs) == ["TPU-L002", "TPU-L002"]


def test_l002_host_pool_is_exempt():
    vs = _lint("""
        from concurrent.futures import ThreadPoolExecutor
        pool = ThreadPoolExecutor(4)
    """, relpath="runtime/host_pool.py")
    assert _rules(vs) == []


def test_l003_raw_ns_timer_in_exec_layer():
    src = """
        class X:
            def f(self, m):
                with m.ns():
                    pass
    """
    assert _rules(_lint(src, relpath="exec/nodes.py")) == ["TPU-L003"]
    # outside the exec layer the bare timer is the sanctioned primitive
    assert _rules(_lint(src, relpath="runtime/x.py")) == []


def test_l004_host_sync_in_span_body():
    vs = _lint("""
        class X:
            def f(self, m, arr):
                with self.span(m):
                    v = arr.item()
    """)
    assert _rules(vs) == ["TPU-L004"]


def test_l004_deferred_fetch_annotation_passes():
    vs = _lint("""
        class X:
            def f(self, m, arr):
                with self.span(m):
                    # tpulint: deferred-fetch consumed after yield, rides under compute
                    v = arr.item()
    """)
    assert _rules(vs) == []


def test_l004_jnp_asarray_stays_on_device():
    vs = _lint("""
        class X:
            def f(self, m, arr):
                with self.span(m):
                    a = jnp.asarray(arr)
                    b = np.asarray(arr)
    """)
    assert _rules(vs) == ["TPU-L004"]  # only the np.asarray


def test_l005_mutable_default():
    vs = _lint("""
        def f(a, out=[], opts={}):
            pass
        def g(a, out=None):
            pass
    """)
    assert _rules(vs) == ["TPU-L005", "TPU-L005"]


def test_l006_swallowed_exception():
    vs = _lint("""
        try:
            risky()
        except Exception:
            pass
    """)
    assert _rules(vs) == ["TPU-L006"]


def test_l006_justified_swallow_passes():
    vs = _lint("""
        try:
            risky()
        except Exception:  # noqa: BLE001 - best-effort cleanup, error reported upstream
            pass
    """)
    assert _rules(vs) == []


def test_l007_unregistered_metric_name():
    vs = _lint("""
        class X:
            def f(self):
                t = self.metrics.metric("bogusTime")
                u = self.metrics.metric("opTime")
    """)
    assert _rules(vs) == ["TPU-L007"]


def _lint_sites(src, sites=frozenset({"scan.decode", "shuffle.read"})):
    return lint.lint_source(textwrap.dedent(src), "/x/runtime/x.py",
                            {"opTime"}, relpath="runtime/x.py",
                            known_sites=set(sites))


def test_l008_unregistered_fault_site():
    vs = _lint_sites("""
        from spark_rapids_tpu.runtime import faults
        def f(data):
            faults.site("scan.decode")
            faults.site("made.up.site")
            data = faults.site_bytes("also.bogus", data)
            return faults.site_bytes("shuffle.read", data)
    """)
    assert _rules(vs) == ["TPU-L008", "TPU-L008"]


def test_l008_only_fault_injector_receivers_match():
    # .site() on an unrelated receiver (an HTTP client, a config object)
    # is not a fault-injection point
    vs = _lint_sites("""
        def f(client, data):
            client.site("whatever.name")
            return data
    """)
    assert _rules(vs) == []


def test_l008_roster_extraction_matches_faults_module():
    sites = lint.known_fault_sites(
        os.path.join(REPO, "spark_rapids_tpu"))
    from spark_rapids_tpu.runtime.faults import SITES
    assert sites == set(SITES)
    assert {"scan.decode", "shuffle.read", "shuffle.write", "spill.disk",
            "device.dispatch", "pipeline.producer", "exchange.fetch",
            "retry.oom"} <= sites


def test_l008_skipped_without_roster():
    # lint_source without known_sites (older fixtures, partial runs)
    # must not report L008
    vs = _lint("""
        from spark_rapids_tpu.runtime import faults
        def f():
            faults.site("made.up.site")
    """)
    assert _rules(vs) == []


def _lint_buckets(src, buckets=frozenset({"compile", "device_compute"})):
    return lint.lint_source(textwrap.dedent(src), "/x/runtime/x.py",
                            {"opTime"}, relpath="runtime/x.py",
                            known_buckets=set(buckets))


def test_l009_unregistered_bucket():
    vs = _lint_buckets("""
        from spark_rapids_tpu.runtime.obs import attribution as _attr
        def f(ns):
            _attr.record("compile", ns)
            _attr.record("made_up_bucket", ns)
    """)
    assert _rules(vs) == ["TPU-L009"]


def test_l009_only_attribution_receivers_match():
    # .record() on an unrelated receiver (a history store, an audio
    # object) is not an attribution point
    vs = _lint_buckets("""
        def f(store, ns):
            store.record("whatever_name", ns)
    """)
    assert _rules(vs) == []


def test_l009_roster_extraction_matches_attribution_module():
    buckets = lint.known_attr_buckets(
        os.path.join(REPO, "spark_rapids_tpu"))
    from spark_rapids_tpu.runtime.obs.attribution import BUCKETS
    assert buckets == set(BUCKETS)
    assert {"compile", "device_compute", "host_decode", "shuffle",
            "semaphore_wait", "pipeline_stall", "retry_backoff",
            "spill", "other"} <= buckets


def test_l009_skipped_without_roster():
    vs = _lint("""
        from spark_rapids_tpu.runtime.obs import attribution
        def f(ns):
            attribution.record("made_up_bucket", ns)
    """)
    assert _rules(vs) == []


def _lint_compile(src, relpath="ops/x.py",
                  pallas=frozenset({"ops/pallas_kernels.py"})):
    return lint.lint_source(textwrap.dedent(src), "/x/" + relpath,
                            {"opTime"}, relpath=relpath,
                            pallas_modules=set(pallas))


def test_l010_raw_jit_flagged():
    vs = _lint_compile("""
        import jax
        from functools import partial
        @jax.jit
        def f(x):
            return x + 1
        @partial(jax.jit, static_argnums=(1,))
        def g(x, n):
            return x[:n]
        def h(step):
            return jax.jit(step)
    """)
    assert _rules(vs) == ["TPU-L010"] * 3


def test_l010_compile_cache_and_wrapper_allowed():
    # the choke point itself, and code routing THROUGH it, are clean
    vs = _lint_compile("""
        import jax
        def get(key, builder):
            return jax.jit(builder())
    """, relpath="runtime/compile_cache.py")
    assert _rules(vs) == []
    vs = _lint_compile("""
        from spark_rapids_tpu.runtime import compile_cache as _cc
        @_cc.jit(static_argnums=(1,))
        def g(x, n):
            return x[:n]
    """)
    assert _rules(vs) == []


def test_l010_pallas_confined_to_roster():
    src = """
        from jax.experimental import pallas as pl
        def k(kern, x):
            return pl.pallas_call(kern, out_shape=x)(x)
    """
    assert _rules(_lint_compile(src, relpath="ops/x.py")) == ["TPU-L010"]
    assert _rules(_lint_compile(
        src, relpath="ops/pallas_kernels.py")) == []


def test_l010_roster_extraction_matches_compile_cache():
    mods = lint.known_pallas_modules(
        os.path.join(REPO, "spark_rapids_tpu"))
    from spark_rapids_tpu.runtime.compile_cache import (
        SANCTIONED_PALLAS_MODULES,
    )
    assert mods == set(SANCTIONED_PALLAS_MODULES)
    assert "ops/pallas_segsum.py" in mods


def _lint_live(src, states=frozenset({"executing", "ok"}),
               series=frozenset({"process_rss_bytes"})):
    return lint.lint_source(textwrap.dedent(src), "/x/runtime/x.py",
                            {"opTime"}, relpath="runtime/x.py",
                            known_states=set(states),
                            known_series=set(series))


def test_l011_unregistered_query_state():
    vs = _lint_live("""
        def f(qc):
            qc.transition("executing")
            qc.transition("warp_speed")
    """)
    assert _rules(vs) == ["TPU-L011"]


def test_l011_unregistered_sampler_series():
    vs = _lint_live("""
        def f(smp, v):
            smp.series_point("process_rss_bytes", v)
            smp.series_point("made_up_series", v)
            smp.sample_series("also_made_up", v)
    """)
    assert _rules(vs) == ["TPU-L011", "TPU-L011"]


def test_l011_non_literal_and_other_calls_skipped():
    vs = _lint_live("""
        def f(qc, state, store):
            qc.transition(state)
            store.record("whatever", 1)
    """)
    assert _rules(vs) == []


def test_l012_unbounded_wait_flagged():
    vs = _lint("""
        import threading
        def f(ev):
            ev.wait()
    """)
    assert _rules(vs) == ["TPU-L012"]


def test_l012_bounded_and_annotated_waits_pass():
    vs = _lint("""
        def f(ev, cv, done):
            ev.wait(5.0)
            cv.wait(timeout=0.25)
            done.wait()  # tpulint: uncancellable shutdown barrier only
            wait()
    """)
    assert _rules(vs) == []


def test_l012_literal_none_timeout_is_unbounded():
    """Event.wait(None) blocks forever — a None timeout must not pass
    as 'bounded'."""
    vs = _lint("""
        def f(ev, cv):
            ev.wait(None)
            cv.wait(timeout=None)
    """)
    assert _rules(vs) == ["TPU-L012", "TPU-L012"]


def test_l012_sanctioned_waiter_protocol_files_exempt():
    src = """
        def f(ev):
            ev.wait()
    """
    assert _rules(_lint(src, relpath="runtime/semaphore.py")) == []
    assert _rules(_lint(src, relpath="runtime/lifecycle.py")) == []
    assert _rules(_lint(src, relpath="analysis/sanitizer.py")) == []
    assert _rules(_lint(src, relpath="runtime/pipeline.py")) \
        == ["TPU-L012"]


def test_l012_suppression_counts():
    vs = _lint("""
        def f(ev):
            ev.wait()  # tpulint: disable=TPU-L012 test fixture wait
    """)
    assert _rules(vs) == []
    assert _rules(vs, suppressed=True) == ["TPU-L012"]


def _lint_kernel(src, relpath="ops/new_kernel.py",
                 roster=frozenset({"ops/kernels.py"})):
    return lint.lint_source(textwrap.dedent(src), "/x/" + relpath,
                            {"opTime"}, relpath=relpath,
                            pallas_modules={"ops/pallas_kernels.py"},
                            kernel_modules=set(roster))


def test_l013_unrostered_cc_jit_module_flagged():
    """A compile_cache.jit site (bare decorator, call-form decorator,
    and plain call) in a module outside KERNEL_PRIMITIVES fails — the
    audit's coverage statement must track every kernel emitter."""
    vs = _lint_kernel("""
        from spark_rapids_tpu.runtime import compile_cache as _cc

        @_cc.jit
        def k1(x):
            return x

        @_cc.jit(static_argnums=(1,))
        def k2(x, n):
            return x

        def k3(fn):
            return _cc.jit(fn)
    """)
    assert _rules(vs) == ["TPU-L013", "TPU-L013", "TPU-L013"]


def test_l013_rostered_module_and_non_kernel_module_pass():
    src = """
        from spark_rapids_tpu.runtime import compile_cache as _cc

        @_cc.jit
        def k(x):
            return x
    """
    assert _rules(_lint_kernel(src, relpath="ops/kernels.py")) == []
    # a module with no kernel sites owes the roster nothing
    assert _rules(_lint_kernel("""
        def plain(x):
            return x + 1
    """)) == []


def test_l013_pallas_call_outside_roster_flagged():
    """pallas_call makes a module kernel-emitting too: a sanctioned
    pallas module (TPU-L010-clean) that is NOT in KERNEL_PRIMITIVES
    still fails L013 — the two rosters enforce different claims."""
    src = """
        import jax.experimental.pallas as pl

        def k(x):
            return pl.pallas_call(lambda r: r, out_shape=x)(x)
    """
    vs = _lint_kernel(src, relpath="ops/pallas_kernels.py")
    assert _rules(vs) == ["TPU-L013"]
    vs2 = _lint_kernel(src, relpath="ops/pallas_kernels.py",
                       roster=frozenset({"ops/pallas_kernels.py"}))
    assert _rules(vs2) == []


def test_decode_module_in_both_rosters_and_clean():
    """Round 16 fixture: ops/pallas_decode.py (the parquet-decode pallas
    kernel home) must be sanctioned in BOTH rosters — TPU-L010's
    SANCTIONED_PALLAS_MODULES and TPU-L013's KERNEL_PRIMITIVES — and its
    real source must lint clean under them."""
    pkg = os.path.join(REPO, "spark_rapids_tpu")
    pallas_mods = lint.known_pallas_modules(pkg)
    kernel_mods = lint.known_kernel_primitives(pkg)
    assert "ops/pallas_decode.py" in pallas_mods
    assert "ops/pallas_decode.py" in kernel_mods
    path = os.path.join(pkg, "ops", "pallas_decode.py")
    with open(path) as f:
        src = f.read()
    vs = lint.lint_source(src, path, {"opTime"},
                          relpath="ops/pallas_decode.py",
                          pallas_modules=pallas_mods,
                          kernel_modules=kernel_mods)
    assert [r for r in _rules(vs) if r in ("TPU-L010", "TPU-L013")] == []
    # and OUTSIDE the rosters the same source is flagged: the fixture
    # proves the roster entries are load-bearing, not decorative
    vs2 = lint.lint_source(src, path, {"opTime"},
                           relpath="ops/pallas_decode.py",
                           pallas_modules=pallas_mods
                           - {"ops/pallas_decode.py"},
                           kernel_modules=kernel_mods
                           - {"ops/pallas_decode.py"})
    assert "TPU-L010" in _rules(vs2) and "TPU-L013" in _rules(vs2)


def test_l013_roster_extraction_and_staleness():
    pkg = os.path.join(REPO, "spark_rapids_tpu")
    mods = lint.known_kernel_primitives(pkg)
    from spark_rapids_tpu.analysis.kernel_audit import KERNEL_PRIMITIVES
    assert mods == set(KERNEL_PRIMITIVES)
    # every rostered module exists and really emits kernels (the stale
    # half lint_tree enforces on the real tree)
    for mod in mods:
        path = os.path.join(pkg, mod.replace("/", os.sep))
        assert os.path.exists(path), mod
        assert lint.module_emits_kernels(path), mod
    # and a kernel-free module is not kernel-emitting
    assert not lint.module_emits_kernels(
        os.path.join(pkg, "runtime", "metrics.py"))


def _lint_collective(src, relpath="exec/new_shuffle.py",
                     roster=frozenset({"parallel/exchange.py"})):
    return lint.lint_source(textwrap.dedent(src), "/x/" + relpath,
                            {"opTime"}, relpath=relpath,
                            collective_modules=set(roster))


def test_l016_collective_outside_roster_flagged():
    """lax.all_to_all / lax.psum / shard_map are SPMD program structure:
    a call site outside SANCTIONED_COLLECTIVE_MODULES fails — every
    shard must reach the collective and its compiled entry must carry
    the mesh-fingerprint compile key, reasoning the roster keeps local."""
    vs = _lint_collective("""
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental.shard_map import shard_map

        def exchange(x, mesh, spec):
            f = shard_map(lambda s: lax.all_to_all(
                s, "part", 0, 0), mesh=mesh, in_specs=spec,
                out_specs=spec)
            return f(x), lax.psum(jnp.sum(x), "part")
    """)
    assert _rules(vs) == ["TPU-L016", "TPU-L016", "TPU-L016"]


def test_l016_rostered_module_and_plain_code_pass():
    src = """
        from jax import lax
        from jax.experimental.shard_map import shard_map

        def exchange(x, mesh, spec):
            return shard_map(lambda s: lax.all_to_all(s, "part", 0, 0),
                             mesh=mesh, in_specs=spec, out_specs=spec)(x)
    """
    assert _rules(_lint_collective(
        src, relpath="parallel/exchange.py")) == []
    # collective-free modules owe the roster nothing
    assert _rules(_lint_collective("""
        def plain(x):
            return x.all_to_all_like_name  # attribute, not a call
    """)) == []


def test_l016_roster_extraction_and_staleness():
    """known_collective_modules mirrors the runtime roster
    (parallel/mesh.py SANCTIONED_COLLECTIVE_MODULES), every entry
    exists and really calls a collective, and the round-19 modules —
    the sharded-stage planner and the ICI exchange — are rostered."""
    pkg = os.path.join(REPO, "spark_rapids_tpu")
    mods = lint.known_collective_modules(pkg)
    from spark_rapids_tpu.parallel.mesh import \
        SANCTIONED_COLLECTIVE_MODULES
    assert mods == set(SANCTIONED_COLLECTIVE_MODULES)
    assert "exec/sharded.py" in mods
    assert "exec/tpu_nodes.py" in mods
    for mod in mods:
        path = os.path.join(pkg, mod.replace("/", os.sep))
        assert os.path.exists(path), mod
        assert lint.module_uses_collectives(path), mod
    assert not lint.module_uses_collectives(
        os.path.join(pkg, "runtime", "metrics.py"))


def _lint_routes(src, routes=frozenset({"/metrics", "/healthz"})):
    return lint.lint_source(textwrap.dedent(src), "/x/runtime/obs/x.py",
                            {"opTime"}, relpath="runtime/obs/x.py",
                            known_routes=set(routes))


def test_l014_off_roster_route_flagged():
    vs = _lint_routes("""
        def do_GET(self, path):
            if path == "/metrics":
                pass
            elif path in ("/healthz", "/secret"):
                pass
    """)
    assert _rules(vs) == ["TPU-L014"]


def test_l014_non_path_compare_and_suppression():
    # `opname == "/"` (the UDF-compiler shape) must never match: the
    # variable has to terminate in exactly `path`
    assert _rules(_lint_routes("""
        def compile_op(opname):
            if opname == "/":
                return "div"
    """)) == []
    vs = _lint_routes("""
        def do_GET(self, path):
            if path == "/debug":  # tpulint: disable=TPU-L014 dev route
                pass
    """)
    assert _rules(vs) == []
    assert _rules(vs, suppressed=True) == ["TPU-L014"]


def test_l014_skipped_without_roster():
    assert _rules(_lint("""
        def do_GET(self, path):
            if path == "/unregistered":
                pass
    """)) == []


def test_l014_roster_extraction_served_and_documented():
    pkg = os.path.join(REPO, "spark_rapids_tpu")
    from spark_rapids_tpu.runtime.obs.endpoint import ROUTES
    routes = lint.known_http_routes(pkg)
    assert routes == set(ROUTES)
    assert {"/metrics", "/healthz", "/serving", "/sql"} <= routes
    # the stale half's input: every non-templated roster entry really is
    # dispatched by a handler Compare in the endpoint source
    served = lint.endpoint_served_routes(
        os.path.join(pkg, "runtime", "obs", "endpoint.py"))
    assert {r for r in routes if "<" not in r} <= served
    # and the generated docs carry every roster route
    documented = lint.docs_route_names(REPO)
    assert documented is not None and routes <= documented


def _lint_reqtrace(src, relpath="runtime/obs/x.py",
                   spans=frozenset({"intake", "execute"}),
                   verdicts=frozenset({"error", "sampled"}),
                   collect=None):
    return lint.lint_source(textwrap.dedent(src), "/x/" + relpath,
                            {"opTime"}, relpath=relpath,
                            known_request_spans=set(spans),
                            known_verdicts=set(verdicts),
                            collect=collect)


def test_l015_off_roster_span_flagged():
    vs = _lint_reqtrace("""
        def handle(self, ctx):
            with RT.request_span("intake"):
                pass
            with rec.request_span(ctx, "mystery_phase"):
                pass
    """)
    assert _rules(vs) == ["TPU-L015"]
    assert "mystery_phase" in vs[0].message


def test_l015_off_roster_verdict_flagged_and_scoped():
    src = """
        def decide(self):
            return _v("weird_outcome")
    """
    assert _rules(_lint_reqtrace(src)) == ["TPU-L015"]
    # the _v shape is the reqtrace/serving verdict checkpoint — an
    # unrelated _v helper elsewhere in the engine must never match
    assert _rules(_lint_reqtrace(src, relpath="exec/x.py")) == []


def test_l015_suppression_and_skipped_without_roster():
    vs = _lint_reqtrace("""
        def handle(self):
            with RT.request_span("debug_phase"):  # tpulint: disable=TPU-L015 experiment
                pass
    """)
    assert _rules(vs) == []
    assert _rules(vs, suppressed=True) == ["TPU-L015"]
    assert _rules(lint.lint_source(textwrap.dedent("""
        def handle(self):
            with RT.request_span("anything"):
                pass
    """), "/x/runtime/obs/x.py", {"opTime"},
        relpath="runtime/obs/x.py")) == []


def test_l015_collect_aggregates_call_sites():
    used = {}
    _lint_reqtrace("""
        def handle(self):
            with RT.request_span("intake"):
                return _v("error")
    """, collect=used)
    assert used["request_spans"] == {"intake"}
    assert used["verdicts"] == {"error"}


def test_l015_roster_extraction_used_and_documented():
    pkg = os.path.join(REPO, "spark_rapids_tpu")
    from spark_rapids_tpu.runtime.obs.reqtrace import (REQUEST_SPANS,
                                                       VERDICTS)
    spans = lint.known_request_spans(pkg)
    verdicts = lint.known_reqtrace_verdicts(pkg)
    assert spans == set(REQUEST_SPANS)
    assert verdicts == set(VERDICTS)
    assert {"intake", "admission_wait", "execute", "serialize"} <= spans
    assert {"error", "cancelled", "deadline", "slo_breach", "sampled",
            "dropped"} <= verdicts
    # the generated docs carry every roster name (the docs-presence half)
    documented = lint.docs_metric_names(REPO)
    assert documented is not None
    assert spans <= documented and verdicts <= documented
    # every verdict is used by the decide() checkpoints in the real
    # source (the stale half's input)
    used = {}
    rtpath = os.path.join(pkg, "runtime", "obs", "reqtrace.py")
    lint.lint_source(open(rtpath).read(), rtpath,
                     {"opTime"}, relpath="runtime/obs/reqtrace.py",
                     known_verdicts=verdicts, collect=used)
    assert verdicts <= used["verdicts"]


def test_l011_roster_extraction_matches_live_modules():
    pkg = os.path.join(REPO, "spark_rapids_tpu")
    from spark_rapids_tpu.runtime.obs.live import STATES
    from spark_rapids_tpu.runtime.obs.sampler import SERIES
    assert lint.known_query_states(pkg) == set(STATES)
    assert lint.known_sampler_series(pkg) == set(SERIES)
    assert {"queued", "planning", "executing", "finishing", "ok",
            "failed", "degraded", "cancelled"} == set(STATES)
    assert {"device_bytes_held", "semaphore_waiting", "breaker_state",
            "process_rss_bytes",
            "pipeline_stalled_consumers"} <= set(SERIES)


def test_l011_skipped_without_roster():
    vs = _lint("""
        def f(qc):
            qc.transition("warp_speed")
    """)
    assert _rules(vs) == []


def test_lint_full_tree_is_clean():
    """The acceptance bar: zero unsuppressed violations over the whole
    package, <=5 suppressions, every one carrying a reason."""
    violations, stats = lint.lint_tree(REPO)
    live = [v.render(REPO) for v in violations if not v.suppressed]
    assert live == [], "\n".join(live)
    assert stats["suppressed"] <= 5
    assert stats["suppressions_without_reason"] == 0


# ---------------------------------------------------------------------------
# Runtime concurrency sanitizer: seeded bugs must be caught
# ---------------------------------------------------------------------------

@pytest.fixture
def san():
    # 250ms default: nested-acquire stack capture under an outer lock
    # must not fake a held-lock finding on a loaded CI box; tests about
    # hold detection re-install with their own tight threshold
    sanitizer.uninstall()
    sanitizer.install(hold_warn_ms=250.0)
    yield sanitizer
    sanitizer.uninstall()


def _kinds(rep):
    return [f["kind"] for f in rep["findings"]]


def test_sanitizer_seeded_lock_inversion(san):
    a, b = san.lock("seed.A"), san.lock("seed.B")
    with a:
        with b:
            pass
    assert _kinds(san.report()) == []  # one order alone is legal
    with b:
        with a:
            pass
    rep = san.report()
    inv = [f for f in rep["findings"] if f["kind"] == "lock-inversion"]
    assert len(inv) == 1
    assert sorted(inv[0]["locks"]) == ["seed.A", "seed.B"]
    assert inv[0]["stack"] and inv[0]["stack_held"]
    # dedup: exhibiting the inversion again does not re-report
    with b:
        with a:
            pass
    assert len([f for f in san.report()["findings"]
                if f["kind"] == "lock-inversion"]) == 1


def test_sanitizer_seeded_cross_thread_inversion(san):
    """The classic ABBA across two threads, sequenced so it cannot
    actually deadlock — the sanitizer must report it from order evidence
    alone."""
    a, b = san.lock("xt.A"), san.lock("xt.B")
    done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        done.set()

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    assert done.wait(5)
    with b:
        with a:
            pass
    inv = [f for f in san.report()["findings"]
           if f["kind"] == "lock-inversion"]
    assert len(inv) == 1 and sorted(inv[0]["locks"]) == ["xt.A", "xt.B"]


def test_sanitizer_seeded_held_lock_blocking(san):
    san.uninstall()
    san.install(hold_warn_ms=5.0)
    lk = san.lock("seed.hold")
    with lk:
        time.sleep(0.02)  # the runtime signature of I/O under a lock
    rep = san.report()
    holds = [f for f in rep["findings"]
             if f["kind"] == "held-lock-blocking"]
    assert len(holds) == 1
    assert holds[0]["locks"] == ["seed.hold"]
    assert holds[0]["held_ms"] >= 5.0 and holds[0]["stack"]


def test_sanitizer_seeded_wait_under_foreign_lock(san):
    other = san.lock("seed.other")
    cv = san.condition("seed.cv")
    with other:
        with cv:
            cv.wait(timeout=0.01)
    waits = [f for f in san.report()["findings"]
             if f["kind"] == "wait-under-lock"]
    assert len(waits) == 1
    assert waits[0]["locks"][0] == "seed.cv"
    assert "seed.other" in waits[0]["locks"]


def test_sanitizer_wait_on_own_cv_alone_is_clean(san):
    cv = san.condition("solo.cv")
    with cv:
        cv.wait(timeout=0.01)
    assert [f for f in san.report()["findings"]
            if f["kind"] == "wait-under-lock"] == []


def test_sanitizer_report_ranking(san):
    san.uninstall()
    san.install(hold_warn_ms=5.0)
    a, b = san.lock("rank.A"), san.lock("rank.B")
    with a:
        time.sleep(0.02)  # hold finding (severity 2)
    with a:
        with b:
            pass
    with b:
        with a:
            pass  # inversion finding (severity 0)
    kinds = _kinds(san.report())
    assert kinds[0] == "lock-inversion"
    assert kinds[-1] == "held-lock-blocking"


def test_sanitizer_disabled_is_passthrough():
    sanitizer.uninstall()
    lk = sanitizer.lock("off.lock")
    with lk:
        assert lk.locked()
    cv = sanitizer.condition("off.cv")
    with cv:
        cv.wait(timeout=0.01)
    rep = sanitizer.report()
    assert rep == {"enabled": False, "findings": [], "edges": 0}


def test_sanitizer_dump_no_trace_is_noop(san):
    san.uninstall()
    san.install(hold_warn_ms=5.0)
    lk = san.lock("dump.hold")
    with lk:
        time.sleep(0.02)
    rep = san.dump()  # tracing disabled: must not raise, still reports
    assert _kinds(rep) == ["held-lock-blocking"]


def test_sanitizer_conf_installs_via_session(tmp_path):
    sanitizer.uninstall()
    try:
        import pyarrow as pa
        s = TpuSession({"spark.rapids.debug.sanitizer.enabled": True,
                        "spark.rapids.debug.sanitizer.holdWarnMs": 250.0})
        df = s.create_dataframe(pa.table({"a": [1, 2, 3]}))
        df.collect()
        assert sanitizer.enabled()
    finally:
        sanitizer.uninstall()


# ---------------------------------------------------------------------------
# Clean engine under a full NDS-probe query: the sanitizer stays silent
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def nds_dfs():
    sess = TpuSession()
    tables = nds.gen_tables(0.002, seed=7)
    out = {name: sess.create_dataframe(t).cache()
           for name, t in tables.items()}
    return sess, out


def test_sanitizer_silent_on_clean_engine(nds_dfs):
    """A real join+agg NDS query through the full engine (scan, fusion,
    pipeline, semaphore, exchange, host pool) must produce ZERO findings
    — the engine's lock discipline is the thing under test. holdWarnMs
    is raised well above the lint-fix bar so CI scheduler hiccups can't
    fake a held-lock finding."""
    sess, d = nds_dfs
    sanitizer.uninstall()
    sanitizer.install(hold_warn_ms=250.0)
    try:
        for qn in (3, 72):
            df = nds.QUERIES[qn](sess, d)
            df.collect()
        rep = sanitizer.report()
        assert rep["enabled"]
        assert rep["findings"] == [], json.dumps(rep["findings"], indent=1)
        # the run DID exercise the instrumentation, not an empty graph
        assert rep["edges"] > 0 or rep["order_edges"] == []
    finally:
        sanitizer.uninstall()


# ---------------------------------------------------------------------------
# Plan-invariant verifier: seeded-illegal trees + the real engine
# ---------------------------------------------------------------------------

class _Field:
    def __init__(self, name, dtype):
        self.name, self.dtype = name, dtype


class _Schema:
    def __init__(self, *fields):
        self.fields = list(fields)


def _node(clsname, schema, children=(), **attrs):
    n = type(clsname, (), {})()
    n.schema = schema
    n.children = list(children)
    for k, v in attrs.items():
        setattr(n, k, v)
    return n


_AB = _Schema(_Field("a", "int64"), _Field("b", "float64"))


def test_verify_schema_preserving_wrapper_violation():
    scan = _node("ParquetScanExec", _AB)
    filt = _node("FilterExec", _Schema(_Field("c", "int64")), [scan])
    viols = check_plan(filt)
    assert len(viols) == 1 and viols[0].startswith("PV-SCHEMA")
    assert "must preserve its child's schema" in viols[0]


def test_verify_malformed_schema():
    viols = check_plan(_node("ProjectExec", None))
    assert viols and "well-formed" in viols[0]


def test_verify_pipeline_at_root_and_bad_wrap():
    scan = _node("ParquetScanExec", _AB)
    pipe = _node("PipelineExec", _AB, [scan], depth=2)
    viols = check_plan(pipe)  # pipe IS the root here
    assert any("PV-PIPE" in v and "root" in v for v in viols)

    sort = _node("SortExec", _AB, [_node("ParquetScanExec", _AB)])
    pipe2 = _node("PipelineExec", _AB, [sort], depth=0)
    root = _node("ProjectExec", _AB, [pipe2])
    viols = check_plan(root)
    assert any("only host-producing scans" in v for v in viols)
    assert any("depth must be >= 1" in v for v in viols)


def test_verify_tree_cycle():
    n = _node("ProjectExec", _AB)
    n.children = [n]
    viols = check_plan(n)
    assert any("PV-TREE" in v and "cycle" in v for v in viols)


def test_verify_plan_raises_with_violation_list():
    filt = _node("FilterExec", _Schema(_Field("c", "int64")),
                 [_node("ParquetScanExec", _AB)])
    with pytest.raises(PlanVerifyError) as ei:
        verify_plan(filt)
    assert len(ei.value.violations) == 1
    assert "PV-SCHEMA" in str(ei.value)


def test_compare_budget_names_the_dimension():
    diffs = compare_budget({"narrow_dispatches_per_batch": 3,
                            "fused_stages": 1},
                           {"narrow_dispatches_per_batch": 2,
                            "fused_stages": 1,
                            "pipeline_boundaries": 2})
    assert len(diffs) == 2
    assert any(d.startswith("narrow_dispatches_per_batch:") for d in diffs)
    assert any(d.startswith("pipeline_boundaries:") for d in diffs)


def test_plan_verify_conf_runs_in_convert(nds_dfs):
    """spark.rapids.debug.planVerify.enabled verifies every converted
    tree inside convert_plan (and the clean engine passes it)."""
    import pyarrow as pa
    s = TpuSession({"spark.rapids.debug.planVerify.enabled": True})
    df = s.create_dataframe(pa.table({"a": [1, 2, 3, 4]}))
    assert df.collect().num_rows == 4


# ---------------------------------------------------------------------------
# Golden dispatch budgets: every NDS probe plan, pinned
# ---------------------------------------------------------------------------

def test_golden_dispatch_budgets(nds_dfs):
    """Re-derive the per-query dispatch budget of every converted NDS
    probe plan and diff it against tests/golden_plans/
    dispatch_budgets.json. A stage-fusion or pipeline-insertion
    regression fails HERE with the changed dimension named, instead of
    surfacing as silent perf loss in a later bench round. Regenerate
    after intended plan-shape changes: python tools/gen_dispatch_budgets.py
    """
    sess, d = nds_dfs
    with open(GOLDEN) as f:
        doc = json.load(f)
    assert doc["_sf"] == 0.002 and doc["_seed"] == 7
    golden = {int(k): v for k, v in doc["budgets"].items()}
    assert set(golden) == set(nds.QUERIES), \
        "query set drifted — regenerate the golden budgets"
    problems = []
    for qn in sorted(nds.QUERIES):
        df = nds.QUERIES[qn](sess, d)
        exec_root, _meta = sess.prepare_execution(df.plan)
        viols = check_plan(exec_root)
        for v in viols:
            problems.append(f"q{qn}: {v}")
        for diff in compare_budget(dispatch_budget(exec_root), golden[qn]):
            problems.append(f"q{qn}: budget {diff}")
    assert not problems, "\n".join(problems)
