"""TopN (ORDER BY + LIMIT) differential tests: the planner rewrites
Limit(Sort) into threshold selection + small exact sort (TopNExec)."""
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.expr.core import col


def _ref_topn(rows, keys, n):
    return sorted(rows, key=keys)[:n]


def test_topn_basic_desc_with_ties():
    rng = np.random.default_rng(0)
    m = 50_000
    v = rng.integers(0, 1000, m)  # heavy ties
    t = pa.table({"k": np.arange(m, dtype=np.int64), "v": v.astype(np.float64)})
    s = TpuSession()
    from spark_rapids_tpu.exec import tpu_nodes as X
    from spark_rapids_tpu.plan.overrides import convert_plan
    df = s.create_dataframe(t).order_by(col("v").desc(), col("k").asc()).limit(7)
    root, _ = convert_plan(df.plan, s.conf)
    names = []
    def walk(e):
        names.append(type(e).__name__)
        [walk(c) for c in e.children]
    walk(root)
    assert "TopNExec" in names, names
    d = df.to_pydict()
    rows = list(zip(v.tolist(), np.arange(m).tolist()))
    ref = sorted(rows, key=lambda r: (-r[0], r[1]))[:7]
    got = list(zip(d["v"], d["k"]))
    assert got == [(float(a), b) for a, b in ref], (got, ref)


def test_topn_nulls_first_asc():
    t = pa.table({
        "v": pa.array([5.0, None, 3.0, None, 1.0, 4.0]),
        "i": pa.array(list(range(6)), type=pa.int64()),
    })
    s = TpuSession()
    d = (s.create_dataframe(t).order_by(col("v").asc(), col("i").asc())
         .limit(3).to_pydict())
    # Spark asc => nulls first
    assert d["v"] == [None, None, 1.0]
    assert d["i"] == [1, 3, 4]


def test_topn_nulls_last_desc():
    t = pa.table({
        "v": pa.array([5.0, None, 3.0, None, 1.0, 4.0]),
        "i": pa.array(list(range(6)), type=pa.int64()),
    })
    s = TpuSession()
    d = (s.create_dataframe(t).order_by(col("v").desc(), col("i").asc())
         .limit(3).to_pydict())
    assert d["v"] == [5.0, 4.0, 3.0]


def test_topn_limit_exceeds_rows():
    t = pa.table({"v": pa.array([2, 1, 3], type=pa.int64())})
    s = TpuSession()
    d = s.create_dataframe(t).order_by(col("v").asc()).limit(10).to_pydict()
    assert d["v"] == [1, 2, 3]


def test_topn_multi_partition():
    rng = np.random.default_rng(1)
    m = 30_000
    v = rng.uniform(-100, 100, m)
    t = pa.table({"v": v})
    s = TpuSession()
    d = (s.create_dataframe(t, num_partitions=4).order_by(col("v").asc())
         .limit(5).to_pydict())
    assert np.allclose(d["v"], np.sort(v)[:5])


def test_topn_string_primary_falls_back_correct():
    t = pa.table({"s": pa.array(["pear", "apple", "fig", "kiwi", "date"]),
                  "i": pa.array(list(range(5)), type=pa.int64())})
    s = TpuSession()
    d = (s.create_dataframe(t).order_by(col("s").asc()).limit(2).to_pydict())
    assert d["s"] == ["apple", "date"]


def test_topn_int64_extreme_values():
    vals = [2**62, -2**62, 0, 2**62 - 1, -2**62 + 1, 7]
    t = pa.table({"v": pa.array(vals, type=pa.int64())})
    s = TpuSession()
    d = s.create_dataframe(t).order_by(col("v").asc()).limit(3).to_pydict()
    assert d["v"] == sorted(vals)[:3]
