"""Differential aggregate tests (reference hash_aggregate_test.py)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


DATA = {
    "k": pa.array(["a", "b", "a", None, "b", "a", None, "c"]),
    "k2": pa.array([1, 2, 1, 2, None, 1, 2, None], pa.int32()),
    "v": pa.array([10, 20, None, 40, 50, 60, 70, None], pa.int64()),
    "f": pa.array([1.5, float("nan"), 2.5, None, -0.0, 0.0, 3.5, 1.25]),
}


def make_df(s, parts=1):
    return s.create_dataframe(dict(DATA), num_partitions=parts)


@pytest.mark.parametrize("parts", [1, 3])
def test_groupby_sum_count(session, parts):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, parts).group_by(col("k")).agg(
            F.sum("v").alias("sv"), F.count("v").alias("cv"),
            F.count().alias("call")),
        session, ignore_order=True)


@pytest.mark.parametrize("parts", [1, 3])
def test_groupby_min_max_avg(session, parts):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, parts).group_by(col("k")).agg(
            F.min("v").alias("mn"), F.max("v").alias("mx"),
            F.avg("v").alias("av")),
        session, ignore_order=True)


def test_groupby_multiple_keys(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, 2).group_by(col("k"), col("k2")).agg(
            F.sum("v").alias("sv")),
        session, ignore_order=True)


def test_groupby_float_values(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).group_by(col("k")).agg(
            F.sum("f").alias("sf"), F.min("f").alias("mnf"),
            F.max("f").alias("mxf")),
        session, ignore_order=True)


def test_groupby_float_keys_nan_zero(session):
    """NaN groups together; -0.0 and 0.0 group together (Spark)."""
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).group_by(col("f")).agg(F.count().alias("c")),
        session, ignore_order=True)


def test_global_agg(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, 2).agg(
            F.sum("v").alias("sv"), F.count("v").alias("cv"),
            F.min("v").alias("mn"), F.max("v").alias("mx"),
            F.avg("v").alias("av")),
        session)


def test_global_agg_empty_input(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).filter(col("v") > lit(10**9)).agg(
            F.sum("v").alias("sv"), F.count("v").alias("cv")),
        session)


def test_groupby_empty_input(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).filter(col("v") > lit(10**9))
                   .group_by(col("k")).agg(F.sum("v").alias("sv")),
        session, ignore_order=True)


def test_stddev_variance(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).group_by(col("k")).agg(
            F.stddev("v").alias("sd"), F.variance("v").alias("vr"),
            F.stddev_pop("v").alias("sdp"), F.var_pop("v").alias("vrp")),
        session, ignore_order=True, approx_float=1e-9)


def test_first_last(session):
    # group-sorted order makes first/last deterministic per engine; values
    # must agree since both pick from the same (single) valid candidates in
    # groups with one valid row; use such data
    data = {"k": ["a", "a", "b"], "v": [1, None, 3]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data).group_by(col("k")).agg(
            F.first("v").alias("fv"), F.last("v").alias("lv")),
        session, ignore_order=True)


def test_distinct(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, 2).select(col("k"), col("k2")).distinct(),
        session, ignore_order=True)


def test_groupby_computed_key(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).group_by((col("k2") % lit(2)).alias("kk")).agg(
            F.sum("v").alias("sv")),
        session, ignore_order=True)


def test_count_star_groupby(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, 2).group_by(col("k")).count(),
        session, ignore_order=True)


def _wide_table(n=96):
    import numpy as np
    rng = np.random.default_rng(11)
    return pa.table({
        "k": pa.array(np.array(["a", "b", "c", "d"], object)[
            rng.integers(0, 4, n)]),
        "v": pa.array(rng.integers(0, 50, n).astype("int64")),
    })


def test_skip_agg_pass_reduction_ratio():
    # ratio 0.0: the first batch never reduces "enough", so the partial
    # merge pass is skipped and un-merged partials (overlapping keys
    # across batches) flow to the final agg — results must be identical.
    s = TpuSession({"spark.rapids.sql.agg.skipAggPassReductionRatio": 0.0,
                    "spark.rapids.sql.reader.batchSizeRows": 8})
    assert_tpu_and_cpu_are_equal_collect(
        lambda ss: ss.create_dataframe(_wide_table(), num_partitions=2)
        .group_by(col("k")).agg(F.sum("v").alias("sv"),
                                F.count("v").alias("cv")),
        s, ignore_order=True)


def test_agg_force_single_pass():
    s = TpuSession({"spark.rapids.sql.agg.forceSinglePassPartialSort": True,
                    "spark.rapids.sql.reader.batchSizeRows": 8})
    assert_tpu_and_cpu_are_equal_collect(
        lambda ss: ss.create_dataframe(_wide_table(), num_partitions=2)
        .group_by(col("k")).agg(F.sum("v").alias("sv"),
                                F.count("v").alias("cv")),
        s, ignore_order=True)
