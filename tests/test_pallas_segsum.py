"""Pallas sorted-window segmented-reduction tests (interpret mode on the
CPU sim — the same kernel code that runs on hardware; measured 1.9x over
the scatter path on v5e, tools/profile_pallas_segsum.py)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


def _tbl(n=8192, span=3000, seed=5, null_p=0.1):
    rng = np.random.default_rng(seed)
    v = rng.uniform(-1000, 1000, n)
    vals = [None if rng.random() < null_p else float(x) for x in v]
    return pa.table({
        "k": pa.array(rng.integers(0, span, n).astype(np.int64)),
        "v": pa.array(vals, pa.float64()),
        "w": pa.array(np.round(rng.uniform(0, 10, n), 3)),
    })


def _eligible_spy(monkeypatch):
    """Assert the pallas path was actually taken (not silently skipped)."""
    from spark_rapids_tpu.exec.tpu_nodes import _AggKernels
    taken = []
    orig = _AggKernels._pallas_seg_agg

    def spy(self, *a, **k):
        taken.append(True)
        return orig(self, *a, **k)

    monkeypatch.setattr(_AggKernels, "_pallas_seg_agg", spy)
    return taken


def test_pallas_segsum_groupby(session, monkeypatch):
    taken = _eligible_spy(monkeypatch)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl()).group_by("k")
        .agg(F.sum(col("v")).alias("sv"), F.count(col("v")).alias("cv"),
             F.sum(col("w")).alias("sw")),
        session, approx_float=1e-9)
    assert taken, "pallas segsum path was not exercised"


def test_pallas_segsum_with_filter_mask(session, monkeypatch):
    taken = _eligible_spy(monkeypatch)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl(seed=9)).filter(
            col("w") > lit(2.0)).group_by("k")
        .agg(F.sum(col("v")).alias("sv"), F.count(col("k")).alias("ck")),
        session, approx_float=1e-9)
    assert taken


def test_pallas_overflow_falls_back(session, monkeypatch):
    # force the in-graph fallback: a tiny MAX_GROUP_ROWS makes every
    # group "deep", so the scatter branch must produce the results
    from spark_rapids_tpu.ops import pallas_segsum as PS
    taken = _eligible_spy(monkeypatch)
    monkeypatch.setattr(PS, "MAX_GROUP_ROWS", 2)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_tbl(span=3000, seed=3)).group_by("k")
        .agg(F.sum(col("v")).alias("sv")),
        session, approx_float=1e-9)
    assert taken


def test_pallas_ineligible_shapes_still_correct(session):
    # strings keys / avg states stay on the scatter or sort paths
    t = _tbl(n=4096, span=50)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).group_by("k")
        .agg(F.avg(col("v")).alias("av"), F.min(col("w")).alias("mw")),
        session, approx_float=1e-9)


def test_pallas_nan_inf_falls_back(session, monkeypatch):
    # NaN/Inf inputs must take the scatter path (digit encoding with an
    # Inf-derived scale would zero every group) and still match the CPU
    # interpreter's Spark semantics
    taken = _eligible_spy(monkeypatch)
    rng = np.random.default_rng(17)
    n = 8192
    v = rng.uniform(-100, 100, n)
    v[5] = float("inf")
    v[77] = float("-inf")
    v[123] = float("nan")
    t = pa.table({"k": pa.array(rng.integers(0, 3000, n).astype(np.int64)),
                  "v": pa.array(v)})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).group_by("k")
        .agg(F.sum(col("v")).alias("sv")),
        session, approx_float=1e-9)
    assert taken


def _chunk_spy(monkeypatch):
    """Assert the CHUNKED pallas path was actually taken."""
    from spark_rapids_tpu.exec.tpu_nodes import _AggKernels
    taken = []
    orig = _AggKernels._chunked_pallas_agg

    def spy(self, *a, **k):
        taken.append(True)
        return orig(self, *a, **k)

    monkeypatch.setattr(_AggKernels, "_chunked_pallas_agg", spy)
    return taken


def _big_tbl(n, span, seed=21, null_p=0.08):
    rng = np.random.default_rng(seed)
    v = rng.uniform(-1000, 1000, n)
    mask = rng.random(n) < null_p
    va = pa.array(np.round(v, 3), pa.float64(), mask=mask)
    return pa.table({
        "k": pa.array(rng.integers(0, span, n).astype(np.int64)),
        "v": va,
    })


def test_chunked_pallas_groupby(session, monkeypatch):
    # cap 32768 = 2 chunks of a shrunken CHUNK_ROWS; span 1600 -> 11
    # packed bits -> nb 2048, so the 2*2048-row partial merge is cheap
    from spark_rapids_tpu.ops import pallas_segsum as PS
    monkeypatch.setattr(PS, "CHUNK_ROWS", 16384)
    taken = _chunk_spy(monkeypatch)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_big_tbl(32768, 1600)).group_by("k")
        .agg(F.sum(col("v")).alias("sv"), F.count(col("v")).alias("cv"),
             F.count(lit(1)).alias("ca")),
        session, approx_float=1e-9, ignore_order=True)
    assert taken, "chunked pallas path was not exercised"


def test_chunked_pallas_four_chunks_filter_mask(session, monkeypatch):
    # 4 chunks: span 1600 packs to 12 bits -> nb 4096, so the merge-cost
    # gate (k * nb <= CHUNK_ROWS) needs CHUNK_ROWS >= 16384
    from spark_rapids_tpu.ops import pallas_segsum as PS
    monkeypatch.setattr(PS, "CHUNK_ROWS", 16384)
    taken = _chunk_spy(monkeypatch)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_big_tbl(65536, 1600, seed=4))
        .filter(col("v") > lit(-500.0)).group_by("k")
        .agg(F.sum(col("v")).alias("sv"), F.count(col("k")).alias("ck")),
        session, approx_float=1e-9, ignore_order=True)
    assert taken


def test_chunked_pallas_nan_chunk_falls_back(session, monkeypatch):
    # NaN in ONE chunk: that chunk takes its scatter fallback, the other
    # chunks stay on the kernel; merged result still matches the CPU tier
    from spark_rapids_tpu.ops import pallas_segsum as PS
    monkeypatch.setattr(PS, "CHUNK_ROWS", 16384)
    taken = _chunk_spy(monkeypatch)
    rng = np.random.default_rng(11)
    n = 32768
    v = rng.uniform(-100, 100, n)
    v[20000] = float("nan")
    v[20001] = float("inf")
    t = pa.table({"k": pa.array(rng.integers(0, 1600, n).astype(np.int64)),
                  "v": pa.array(v)})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).group_by("k")
        .agg(F.sum(col("v")).alias("sv"), F.count(col("v")).alias("cv")),
        session, approx_float=1e-9, ignore_order=True)
    assert taken


def test_chunked_pallas_dict_string_key(session, monkeypatch):
    # dict-encoded string keys share one vocab across chunk partials;
    # vocab must exceed the tiny-bucket MXU limit (4096) to reach the
    # packed-radix path, and 5000 keys pack to 14 bits -> nb 16384
    from spark_rapids_tpu.ops import pallas_segsum as PS
    monkeypatch.setattr(PS, "CHUNK_ROWS", 32768)
    taken = _chunk_spy(monkeypatch)
    rng = np.random.default_rng(7)
    n = 65536
    vocab = [f"key_{i:04d}" for i in range(5000)]
    keys = [vocab[i] for i in rng.integers(0, len(vocab), n)]
    t = pa.table({"k": pa.array(keys),
                  "v": pa.array(np.round(rng.uniform(0, 50, n), 3))})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).group_by("k")
        .agg(F.sum(col("v")).alias("sv")),
        session, approx_float=1e-9, ignore_order=True)
    assert taken
