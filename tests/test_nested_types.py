"""Differential tests for nested types: arrays, structs, maps, explode.

Reference parity: integration_tests array_test.py / struct_test.py /
map_test.py / generate_expr_test.py (GpuGenerateExec,
complexTypeExtractors.scala semantics: null/empty arrays, nested nulls,
outer explode ordering).
"""
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect, assert_fallback_collect
from data_gen import (
    ArrayGen, IntegerGen, LongGen, DoubleGen, StringGen, StructGen, MapGen,
    RepeatSeqGen, gen_df,
)


@pytest.fixture
def session():
    return TpuSession()


def _nested_table():
    return pa.table({
        "k": pa.array([1, 2, 3, 4, 5], pa.int32()),
        "a": pa.array([[1, 2], [], None, [3, None, 5], [6]],
                      pa.list_(pa.int64())),
        "sa": pa.array([["x", "y"], None, [], ["z"], [None, "w"]],
                       pa.list_(pa.string())),
        "st": pa.array([{"x": 1, "y": "p"}, {"x": None, "y": "q"}, None,
                        {"x": 4, "y": None}, {"x": 5, "y": "r"}],
                       pa.struct([("x", pa.int64()), ("y", pa.string())])),
        "m": pa.array([[("a", 1.0)], [("b", 2.0), ("c", 3.0)], [], None,
                       [("d", None)]], pa.map_(pa.string(), pa.float64())),
    })


@pytest.mark.parametrize("fn,colname", [
    (F.explode, "a"), (F.explode_outer, "a"),
    (F.posexplode, "a"), (F.posexplode_outer, "a"),
    (F.explode, "sa"), (F.explode_outer, "sa"),
    (F.explode, "m"), (F.explode_outer, "m"),
])
def test_explode_variants(session, fn, colname):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table())
        .select(col("k"), fn(col(colname))),
        session)


def test_explode_preserves_order_after_filter(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table())
        .filter(col("k") != lit(2))
        .select(col("k"), F.explode_outer(col("a")).alias("v")),
        session)


def test_size_element_at_contains(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table()).select(
            F.size(col("a")).alias("sz"),
            F.size(col("m")).alias("szm"),
            F.element_at(col("a"), 1).alias("e1"),
            F.element_at(col("a"), -1).alias("em1"),
            F.element_at(col("m"), "b").alias("mb"),
            col("a").get_item(0).alias("i0"),
            F.array_contains(col("a"), 3).alias("c3")),
        session)


def test_struct_field_access(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table()).select(
            col("st").get_field("x").alias("x"),
            col("st").get_field("y").alias("y"),
            (col("st").get_field("x") + col("k")).alias("xk")),
        session)


def test_map_keys_values(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table()).select(
            F.map_keys(col("m")).alias("mk"),
            F.map_values(col("m")).alias("mv")),
        session)


def test_create_array(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table()).select(
            F.array(col("k"), col("k") * lit(10)).alias("arr")),
        session)


def test_explode_then_aggregate(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table())
        .select(col("k"), F.explode(col("a")).alias("v"))
        .group_by(col("k")).agg(F.sum("v").alias("sv"),
                                F.count("v").alias("cv")),
        session, ignore_order=True)


def test_nested_passthrough_filter_sort_union(session):
    # nested columns ride through filter (mask), sort (gather), union
    # (concat) as payload
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table())
        .filter(col("k") > lit(1)).select(col("k"), col("a"), col("st"),
                                          col("m"), col("sa")),
        session)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table())
        .order_by(col("k").desc()).select(col("k"), col("a"), col("sa"),
                                          col("m")),
        session)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: (lambda df: df.union(df))(
            s.create_dataframe(_nested_table()).select(col("k"), col("a"))),
        session, ignore_order=True)


def test_nested_limit_cache(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table())
        .select(col("k"), col("a")).limit(3),
        session)


def test_gen_nested_random(session):
    spec = [("k", RepeatSeqGen(IntegerGen(min_val=0, max_val=30), length=25)),
            ("a", ArrayGen(LongGen(), max_len=5)),
            ("sa", ArrayGen(StringGen(min_len=0, max_len=6), max_len=4)),
            ("st", StructGen([("p", IntegerGen()),
                              ("q", DoubleGen(no_nans=True))])),
            ("m", MapGen(StringGen(min_len=1, max_len=3), LongGen(),
                         max_len=4))]
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=512, seed=47)
        .select(col("k"), F.explode_outer(col("a")).alias("v")),
        session)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=512, seed=53).select(
            F.size(col("a")).alias("sz"),
            F.element_at(col("a"), 2).alias("e2"),
            col("st").get_field("p").alias("p"),
            F.element_at(col("m"), "ab").alias("mab")),
        session)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=512, seed=59)
        .select(col("k"), F.explode(col("sa")).alias("sv"))
        .group_by(col("sv")).agg(F.count().alias("n")),
        session, ignore_order=True)


def test_nested_join_falls_back(session):
    # nested payload through joins is not yet on device — must fall back
    # with results still correct
    t = _nested_table()
    assert_fallback_collect(
        lambda s: s.create_dataframe(t).join(
            s.create_dataframe({"k": pa.array([1, 2], pa.int32())}),
            on="k", how="inner"),
        session, "Join", ignore_order=True)


def test_explode_with_nested_sibling_falls_back(session):
    # carrying an array column through the row-duplicating explode needs a
    # sized nested gather — must fall back, results still exact
    assert_fallback_collect(
        lambda s: s.create_dataframe(_nested_table())
        .select(col("sa"), F.explode(col("a")).alias("v")),
        session, "Generate")


def test_explode_with_struct_sibling_on_device(session):
    # structs of primitives duplicate fine (row planes only) — stays on TPU
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_nested_table())
        .select(col("st"), F.explode(col("a")).alias("v")),
        session)


def test_order_by_nested_falls_back(session):
    assert_fallback_collect(
        lambda s: s.create_dataframe(_nested_table())
        .order_by(col("a").asc()).select(col("k"), col("a")),
        session, "Sort")


def test_explode_requires_array_or_map(session):
    with pytest.raises(Exception, match="array or map"):
        session.create_dataframe(_nested_table()).select(
            F.explode(col("k")))
