"""Memory runtime tests: spill cascade, retry-OOM, split-retry, injection.

Reference parity: tests/.../RmmSparkRetrySuiteBase + WithRetrySuite +
HashAggregateRetrySuite + spill/SpillFrameworkSuite (SURVEY.md §4.2) —
the OOM-injection fixture pattern, adapted to the cooperative budget.
"""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.batch import from_pydict
from spark_rapids_tpu.runtime.memory import (
    SpillFramework, SpillableColumnarBatch, reset_spill_framework,
)
from spark_rapids_tpu.runtime.retry import (
    OomInjector, TpuRetryOOM, TpuSplitAndRetryOOM, with_retry,
    with_retry_no_split,
)
from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit


def _batch(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return from_pydict({"a": rng.integers(0, 50, n),
                        "b": rng.uniform(0, 1, n)})


def test_spill_handle_roundtrip_tiers():
    fw = SpillFramework(1 << 30, 1 << 30)
    b = _batch(64)
    h = fw.register(b)
    expect = b.columns[0].data.copy()
    assert h.tier == "device"
    assert h.spill_to_host() == h.size
    assert h.tier == "host"
    assert h.spill_to_disk() == h.size
    assert h.tier == "disk"
    back = h.get()
    assert h.tier == "device"
    np.testing.assert_array_equal(np.asarray(back.columns[0].data),
                                  np.asarray(expect))
    h.close()


def test_reserve_spills_largest_first():
    big, small = _batch(4096, 1), _batch(64, 2)
    fw = SpillFramework(big.device_memory_size()
                        + small.device_memory_size() + 1024, 1 << 30)
    hb, hs = fw.register(big), fw.register(small)
    fw.reserve(2048)  # must evict someone; biggest first
    assert hb.tier == "host"
    assert hs.tier == "device"
    assert fw.metrics["spill_count"] == 1


def test_reserve_cascades_to_disk():
    b1, b2 = _batch(1024, 1), _batch(1024, 2)
    host_budget = b1.device_memory_size() // 2  # host can't hold a batch
    fw = SpillFramework(b1.device_memory_size() + 512, host_budget)
    h1 = fw.register(b1)
    h2 = fw.register(b2)  # over budget already; reserve forces the drain
    fw.reserve(1024)
    tiers = sorted([h1.tier, h2.tier])
    assert "disk" in tiers  # spilled through host to disk
    assert fw.metrics["spill_to_disk_bytes"] > 0


def test_reserve_raises_when_nothing_spillable():
    fw = SpillFramework(1 << 20, 1 << 30)
    with pytest.raises(TpuRetryOOM):
        fw.reserve(1 << 21)  # larger than the whole budget


def test_with_retry_injected_retry_succeeds():
    OomInjector.configure(num_ooms=2)
    calls = []

    def attempt(b):
        calls.append(1)
        return int(b.num_rows)

    out = list(with_retry(attempt, _batch(10)))
    assert out == [10]
    assert len(calls) == 1  # injector fired before the attempt ran


def test_with_retry_split_produces_partials():
    OomInjector.configure(num_ooms=1, split=True)
    seen = []

    def attempt(b):
        seen.append(int(b.num_rows))
        return int(b.num_rows)

    out = list(with_retry(attempt, _batch(10)))
    assert sum(out) == 10
    assert len(out) == 2  # split in half, both halves processed


def test_with_retry_split_cascades_to_single_row_limit():
    OomInjector.configure(num_ooms=100, split=True)
    with pytest.raises(TpuSplitAndRetryOOM):
        list(with_retry(lambda b: 1, _batch(2)))


def test_with_retry_no_split():
    OomInjector.configure(num_ooms=1)
    assert with_retry_no_split(lambda: 42) == 42


def test_agg_with_injected_split_retry_correct():
    # end-to-end: injected split-retry inside the aggregate update must not
    # change results (reference HashAggregateRetrySuite + inject_oom mark)
    t = pa.table({"k": ["a", "b"] * 32, "v": list(range(64))})
    plain = TpuSession().create_dataframe(t).group_by("k") \
        .agg(F.sum(col("v"))).collect().to_pylist()
    s = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "1,0,split"})
    injected = s.create_dataframe(t).group_by("k") \
        .agg(F.sum(col("v"))).collect().to_pylist()
    assert sorted(map(tuple, (r.items() for r in injected))) == \
        sorted(map(tuple, (r.items() for r in plain)))


def test_cache_pages_out_under_tiny_budget():
    # a budget smaller than two cached partitions forces the cache to page
    reset_spill_framework()
    t = pa.table({"x": np.arange(20000, dtype=np.int64),
                  "y": np.random.default_rng(0).uniform(0, 1, 20000)})
    s = TpuSession({"spark.rapids.memory.tpu.budgetBytes": 400_000})
    df = s.create_dataframe(t).cache()
    assert df.count() == 20000
    # run several queries; each rematerialization may evict the other
    assert df.filter(col("x") > lit(10000)).count() == 9999
    got = df.agg(F.sum(col("x"))).to_pydict()
    assert list(got.values())[0][0] == 20000 * 19999 // 2
    reset_spill_framework()


def test_leak_audit_reports_unreleased_handles():
    # reference RapidsBufferCatalog leak tracking: an unreleased handle is
    # named with its registration stack; releasing clears the report
    from spark_rapids_tpu.runtime.memory import SpillFramework
    from spark_rapids_tpu.columnar.batch import ColumnarBatch, ColumnVector
    from spark_rapids_tpu import types as T
    import jax.numpy as jnp
    fw = SpillFramework(1 << 20, 1 << 20)
    fw.leak_audit = True
    b = ColumnarBatch([ColumnVector(T.INT64, jnp.zeros(128, jnp.int64))], 128)
    h = fw.register(b)
    leaks = fw.leak_report()
    assert len(leaks) == 1 and leaks[0][2] is not None
    assert "register" in leaks[0][2] or "test_leak" in leaks[0][2]
    import pytest as _pt
    with _pt.raises(AssertionError, match="not released"):
        fw.assert_no_leaks()
    fw.unregister(h)
    assert fw.leak_report() == []
    fw.assert_no_leaks()
    # expected_live tolerates legitimately persistent registrations
    h2 = fw.register(b)
    fw.assert_no_leaks(expected_live=1)
    fw.unregister(h2)


# ---------------------------------------------------------------------------
# per-task accumulators (GpuTaskMetrics analog) + trace event log
# ---------------------------------------------------------------------------

def _traced_conf(tmp, **extra):
    from spark_rapids_tpu import config as C
    d = {"spark.rapids.sql.trace.enabled": "true",
         "spark.rapids.sql.trace.path": str(tmp)}
    d.update(extra)
    return C.RapidsConf(d)


def _task_rollups(paths):
    import json
    out = []
    with open(paths["events"]) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "task":
                out.append(rec)
    return out


def _instants(paths):
    import json
    with open(paths["trace"]) as f:
        return [e for e in json.load(f)["traceEvents"] if e["ph"] == "i"]


def test_retry_accumulators_roll_up_under_injection(tmp_path):
    # injected retry-OOMs must land in the task rollup AND as instant
    # events in the trace (reference GpuTaskMetrics retryCount +
    # ProfilerOnExecutor artifacts)
    from spark_rapids_tpu.runtime import trace
    from spark_rapids_tpu.runtime.task import TaskContext
    tr = trace.start_query(_traced_conf(tmp_path))
    try:
        OomInjector.configure(num_ooms=2)
        with TaskContext(partition_id=0) as ctx:
            out = list(with_retry(lambda b: int(b.num_rows), _batch(10)))
            assert out == [10]
            assert ctx.metric("retryCount").value == 2
    finally:
        paths = trace.end_query(tr)
        OomInjector.configure(0)
    recs = _task_rollups(paths)
    assert any(r["metrics"].get("retryCount") == 2 for r in recs)
    assert sum(1 for e in _instants(paths) if e["name"] == "retryOOM") == 2


def test_split_retry_accumulators_and_instants(tmp_path):
    from spark_rapids_tpu.runtime import trace
    from spark_rapids_tpu.runtime.task import TaskContext
    tr = trace.start_query(_traced_conf(tmp_path))
    try:
        OomInjector.configure(num_ooms=1, split=True)
        with TaskContext(partition_id=0) as ctx:
            out = list(with_retry(lambda b: int(b.num_rows), _batch(10)))
            assert sum(out) == 10 and len(out) == 2
            assert ctx.metric("splitAndRetryCount").value == 1
    finally:
        paths = trace.end_query(tr)
        OomInjector.configure(0)
    recs = _task_rollups(paths)
    assert any(r["metrics"].get("splitAndRetryCount") == 1 for r in recs)
    assert any(e["name"] == "splitAndRetryOOM" for e in _instants(paths))


def test_spill_accumulators_and_instants(tmp_path):
    # a reservation-forced spill charges the spilling TASK's accumulators
    # (bytes + time) and emits spillToHost instants with byte counts
    from spark_rapids_tpu.runtime import trace
    from spark_rapids_tpu.runtime.task import TaskContext
    tr = trace.start_query(_traced_conf(tmp_path))
    try:
        big = _batch(4096, 1)
        small = _batch(64, 2)
        fw = SpillFramework(big.device_memory_size()
                            + small.device_memory_size() + 1024, 1 << 30)
        with TaskContext(partition_id=3) as ctx:
            hb, hs = fw.register(big), fw.register(small)
            fw.reserve(2048)
            assert hb.tier == "host"
            assert ctx.metric("spillToHostBytes").value == hb.size
            assert ctx.metric("spillToHostTime").value > 0
            assert ctx.metric("maxDeviceBytesHeld").value >= hb.size
            hb.close(); hs.close()
    finally:
        paths = trace.end_query(tr)
    recs = _task_rollups(paths)
    rec = next(r for r in recs if r["partition_id"] == 3)
    assert rec["metrics"]["spillToHostBytes"] > 0
    assert rec["metrics"]["maxDeviceBytesHeld"] > 0
    ev = [e for e in _instants(paths) if e["name"] == "spillToHost"]
    assert ev and ev[0]["args"]["bytes"] > 0


def test_end_to_end_injection_query_traces_retries(tmp_path):
    # extend the existing end-to-end injection test with the trace layer:
    # same results AND the retry shows up in the query's event log
    t = pa.table({"k": ["a", "b"] * 32, "v": list(range(64))})
    s = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "1",
                    "spark.rapids.sql.trace.enabled": "true",
                    "spark.rapids.sql.trace.path": str(tmp_path)})
    got = s.create_dataframe(t).group_by("k") \
        .agg(F.sum(col("v"))).collect().to_pylist()
    assert sorted(r["k"] for r in got) == ["a", "b"]
    recs = _task_rollups(s.last_trace_paths)
    assert any(r["metrics"].get("retryCount", 0) >= 1 for r in recs)
    assert any(e["name"] == "retryOOM"
               for e in _instants(s.last_trace_paths))
