"""Live observability tests: registry (concurrency, histogram quantiles,
Prometheus rendering), /metrics + /healthz endpoint, query history store
(round-trip, digest stability, failure records), EXPLAIN ANALYZE, retry
re-execution accounting, and the history/trace cross-links.

Reference parity: the SQL-UI metric surface + driver-side liveness
registry (SURVEY.md §5.5 / :170) recast for a standalone engine: a
scrapeable process registry, a health signal, and a history store that
survives the process.
"""
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu.expr.core import SparkException, col, lit
from spark_rapids_tpu.runtime import obs
from spark_rapids_tpu.runtime.obs.history import (QueryHistoryStore,
                                                  plan_digest)
from spark_rapids_tpu.runtime.obs.registry import (Counter, Histogram,
                                                   MetricsRegistry)
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.session import TpuSession

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import obs_smoke  # noqa: E402
import profiler_report as PR  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Each test gets its own obs singleton (ports, history dirs)."""
    obs.shutdown_for_tests()
    yield
    obs.shutdown_for_tests()


def _table(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 40, n),
                     "v": rng.integers(1, 1000, n),
                     "d": rng.uniform(0, 1, n)})


def _query(s, t=None):
    return (s.create_dataframe(t if t is not None else _table(),
                               num_partitions=2)
            .filter(col("v") > lit(10))
            .select(col("k"), (col("v") * lit(2)).alias("v2"))
            .group_by("k").agg(F.sum(col("v2")).alias("sv")).collect())


# ---------------------------------------------------------------------------
# registry primitives
# ---------------------------------------------------------------------------

def test_counter_concurrent_publish_no_lost_updates():
    c = Counter("c")
    n_threads, per = 16, 5000

    def worker():
        for _ in range(per):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per


def test_registry_concurrent_publish_from_host_pool():
    # the deployment shape: host-pool worker threads all folding task
    # accumulators into the SAME registry instruments
    from spark_rapids_tpu.runtime.host_pool import (get_host_pool,
                                                    reset_host_pool)
    reg = MetricsRegistry()

    def publish(i):
        reg.counter("rapids_test_total").inc(2)
        reg.histogram("rapids_test_ms").observe(float(i % 50 + 1))
        return i

    reset_host_pool()
    try:
        pool = get_host_pool()
        out = list(pool.map_ordered(publish, range(400)))
        assert out == list(range(400))
        assert reg.counter("rapids_test_total").value == 800
        assert reg.histogram("rapids_test_ms").count == 400
    finally:
        reset_host_pool()


@pytest.mark.parametrize("dist,seed", [
    ("lognormal", 11), ("lognormal", 12), ("uniform", 13),
    ("exponential", 14), ("bimodal", 15)])
def test_histogram_quantiles_vs_numpy(dist, seed):
    rng = np.random.default_rng(seed)
    n = 5000
    xs = {
        "lognormal": rng.lognormal(3.0, 1.5, n),
        "uniform": rng.uniform(1.0, 1e6, n),
        "exponential": rng.exponential(1e4, n) + 1e-3,
        # 40/60 split keeps p50/p95/p99 INSIDE a mode (at a 50/50 split
        # the true median sits in the empty gap between modes, where
        # nearest-rank and linear interpolation legitimately disagree)
        "bimodal": np.concatenate([rng.normal(100, 5, 2 * n // 5),
                                   rng.normal(1e5, 1e3, 3 * n // 5)]),
    }[dist]
    xs = np.abs(xs) + 1e-9
    h = Histogram("h")
    for x in xs:
        h.observe(float(x))
    for q in (0.50, 0.95, 0.99):
        est = h.quantile(q)
        exact = float(np.percentile(xs, q * 100))
        assert abs(est - exact) / exact < 0.12, \
            (dist, q, est, exact)
    snap = h.snapshot()
    assert snap["count"] == len(xs)
    assert snap["min"] == pytest.approx(float(xs.min()))
    assert snap["max"] == pytest.approx(float(xs.max()))


def test_histogram_memory_is_bounded():
    h = Histogram("h")
    rng = np.random.default_rng(0)
    # 13 orders of magnitude of observations
    for x in 10.0 ** rng.uniform(-3, 10, 100_000):
        h.observe(float(x))
    # 13 decades * log2(10) octaves * 8 sub-buckets ~ 346 max
    assert h.bucket_count() < 400
    assert h.count == 100_000


def test_histogram_edge_cases():
    h = Histogram("h")
    assert h.quantile(0.5) == 0.0  # empty
    h.observe(0.0)
    h.observe(-5.0)
    h.observe(42.0)
    assert h.quantile(0.99) <= 42.0
    assert h.snapshot()["min"] == -5.0


def test_prometheus_render_parseable_and_typed():
    reg = MetricsRegistry()
    reg.counter("rapids_a_total", "a counter").inc(3)
    reg.gauge("rapids_g", "a gauge").set(1.5)
    reg.gauge_fn("rapids_live", lambda: 7, "live gauge",
                 labels={"tier": "t0"})
    h = reg.histogram("rapids_h_ms", "a histogram")
    for v in (1.0, 10.0, 100.0):
        h.observe(v)
    text = reg.render_prometheus()
    n = obs_smoke.check_prometheus(text)  # raises on malformed lines
    assert n >= 7  # 1 counter + 2 gauges + 3 quantiles + sum + count
    assert "# TYPE rapids_a_total counter" in text
    assert "# TYPE rapids_g gauge" in text
    assert "# TYPE rapids_h_ms summary" in text
    assert 'rapids_live{tier="t0"} 7.0' in text
    assert "rapids_h_ms_count 3" in text


def test_registry_type_conflict_fails_fast():
    reg = MetricsRegistry()
    reg.counter("rapids_x")
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("rapids_x")


# ---------------------------------------------------------------------------
# publish path: task + query folding
# ---------------------------------------------------------------------------

def test_task_and_query_publish(tmp_path):
    # historyDir makes the store a rollup consumer; without one (and
    # without a port) the per-exec publish is skipped (no device syncs
    # for series nothing reads — see test below)
    s = TpuSession({"spark.rapids.obs.historyDir": str(tmp_path)})
    _query(s)
    st = obs.state()
    assert st is not None
    snap = st.registry.snapshot()
    assert snap["rapids_tasks_completed_total"] >= 1
    assert snap['rapids_queries_total{status="ok"}'] == 1
    assert snap["rapids_query_wall_time_ms"]["count"] == 1
    # per-exec rollups landed with bounded exec-class labels
    assert any(k.startswith("rapids_exec_rows_total") for k in snap)


def test_exec_rollups_skipped_without_consumer():
    s = TpuSession()  # registry only: no endpoint, no history store
    _query(s)
    snap = obs.state().registry.snapshot()
    assert snap['rapids_queries_total{status="ok"}'] == 1
    assert not any(k.startswith("rapids_exec_") for k in snap)


def test_nested_query_joins_outer_and_unwinds():
    s = TpuSession()
    _query(s)  # installs obs
    before = obs.state().registry.snapshot()['rapids_queries_total'
                                             '{status="ok"}']
    tok = obs.on_query_start()
    assert isinstance(tok, int)
    nested = obs.on_query_start()  # re-entrant on this thread
    assert nested is obs.NESTED

    def end(t):
        obs.on_query_end(t, session=s, plan=None, status="ok",
                         error=None, duration_ns=1,
                         wall_start_unix=time.time(), trace_paths=None)

    end(nested)  # publishes nothing, unwinds depth
    snap = obs.state().registry.snapshot()
    assert snap['rapids_queries_total{status="ok"}'] == before
    end(tok)
    snap = obs.state().registry.snapshot()
    assert snap['rapids_queries_total{status="ok"}'] == before + 1
    # depth fully unwound: the next action is top-level again
    tok2 = obs.on_query_start()
    assert isinstance(tok2, int) and tok2 > tok
    end(tok2)


def test_concurrent_top_level_queries_all_count():
    # overlapping queries from different threads/sessions must each
    # publish (a serving process's /metrics cannot undercount load)
    sessions = [TpuSession() for _ in range(3)]
    errors = []

    def run(s):
        try:
            _query(s)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=run, args=(s,)) for s in sessions]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    snap = obs.state().registry.snapshot()
    assert snap['rapids_queries_total{status="ok"}'] == 3
    assert snap["rapids_query_wall_time_ms"]["count"] == 3


def test_obs_disabled_is_one_global_read():
    assert obs.state() is None
    s = TpuSession({"spark.rapids.obs.enabled": "false"})
    _query(s)
    assert obs.state() is None  # nothing installed, nothing published


# ---------------------------------------------------------------------------
# endpoint
# ---------------------------------------------------------------------------

def test_endpoint_scrape_and_healthz_flip(tmp_path):
    port = obs_smoke._free_port()
    s = TpuSession({"spark.rapids.obs.port": str(port),
                    "spark.rapids.obs.probeTimeoutMs": "400"})
    errors = []

    def driver():
        try:
            for _ in range(2):
                _query(s, _table(100_000))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    th = threading.Thread(target=driver)
    th.start()
    mid = 0
    while th.is_alive():
        code, body = obs_smoke._get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200
        obs_smoke.check_prometheus(body)
        mid += 1
        time.sleep(0.02)
    th.join()
    assert not errors and mid >= 1
    code, body = obs_smoke._get(f"http://127.0.0.1:{port}/metrics")
    for name in obs_smoke.ROSTER:
        assert name in body, name
    code, hz = obs_smoke._get(f"http://127.0.0.1:{port}/healthz")
    doc = json.loads(hz)
    assert code == 200 and doc["status"] == "ok"
    assert doc["device"]["alive"] and doc["semaphore"]["permits"] >= 1
    assert doc["queries"]["completed_ok"] >= 2
    # blocked probe -> degraded + 503 (the liveness acceptance criterion)
    obs.set_device_probe(lambda: time.sleep(30) or True)
    code, hz = obs_smoke._get(f"http://127.0.0.1:{port}/healthz")
    doc = json.loads(hz)
    assert code == 503 and doc["status"] == "degraded"
    assert doc["device"]["blocked"]
    code, _ = obs_smoke._get(f"http://127.0.0.1:{port}/")
    assert code == 200


# ---------------------------------------------------------------------------
# history store
# ---------------------------------------------------------------------------

def test_history_round_trip_and_digest_stability(tmp_path):
    s = TpuSession({"spark.rapids.obs.historyDir": str(tmp_path)})
    _query(s)
    _query(s)
    # a DIFFERENT query must get a different digest
    s.create_dataframe(_table()).filter(col("v") > lit(999)).collect()
    recs = QueryHistoryStore(str(tmp_path)).read_all()
    assert len(recs) == 3
    assert {r["status"] for r in recs} == {"ok"}
    d1, d2, d3 = (r["plan_digest"] for r in recs)
    assert d1 == d2 and d1 != d3
    assert QueryHistoryStore(str(tmp_path)).by_digest(d1) == recs[:2]
    # rollups + plan + conf delta persisted
    r = recs[0]
    assert r["physical_plan"] and r["execs"]
    assert any(v["_rollup"]["rows"] > 0 for v in r["execs"].values())
    assert C.OBS_HISTORY_DIR.key in r["conf_delta"]
    assert r["duration_ns"] > 0 and r["query_id"] == 1


def test_plan_digest_is_process_independent():
    # same logical plan built twice (fresh objects) -> same digest
    s1, s2 = TpuSession(), TpuSession()
    t = _table()
    p1 = s1.create_dataframe(t).filter(col("v") > lit(5)).plan
    p2 = s2.create_dataframe(t).filter(col("v") > lit(5)).plan
    assert plan_digest(p1) == plan_digest(p2)
    p3 = s1.create_dataframe(t).filter(col("v") > lit(6)).plan
    assert plan_digest(p1) != plan_digest(p3)


def test_digest_stable_across_cache_state():
    s = TpuSession()
    df = s.create_dataframe(_table()).cache().filter(col("v") > lit(5))
    d_cold = plan_digest(df.plan)
    df.collect()  # materializes the cache (describe() would flip hot)
    assert plan_digest(df.plan) == d_cold


def test_failed_query_recorded_and_trace_finalized(tmp_path):
    # satellite: a query that raises mid-collect must still flush its
    # trace (with an error marker) and land in history as failed
    s = TpuSession({
        "spark.rapids.obs.historyDir": str(tmp_path / "hist"),
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.path": str(tmp_path / "tr"),
        "spark.sql.ansi.enabled": "true"})
    t = pa.table({"v": [1, 2, 3, 4], "z": [1, 1, 0, 1]})
    df = s.create_dataframe(t).select((col("v") / col("z")).alias("x"))
    with pytest.raises(SparkException):
        df.collect()
    # trace artifacts exist and validate despite the failure
    paths = s.last_trace_paths
    assert paths is not None and os.path.exists(paths["trace"])
    events = PR.validate_chrome_trace(paths["trace"])
    err = [e for e in events if e["ph"] == "i" and e["name"] == "queryError"]
    assert err and err[0]["args"]["error"] == "SparkException"
    with open(paths["events"]) as f:
        qrec = json.loads(f.readline())
    assert qrec["status"] == "failed"
    assert qrec["error_class"] == "SparkException"
    assert qrec["plan_digest"]
    # history: status=failed + exception class (the satellite contract)
    recs = QueryHistoryStore(str(tmp_path / "hist")).read_all()
    assert len(recs) == 1
    assert recs[0]["status"] == "failed"
    assert recs[0]["error_class"] == "SparkException"
    assert recs[0]["plan_digest"] == qrec["plan_digest"]
    # the engine is healthy for the next query
    _query(s)
    recs = QueryHistoryStore(str(tmp_path / "hist")).read_all()
    assert recs[-1]["status"] == "ok"


# ---------------------------------------------------------------------------
# retry re-execution accounting (satellite)
# ---------------------------------------------------------------------------

def _task_rollups(paths):
    out = []
    with open(paths["events"]) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "task":
                out.append(rec)
    return out


def test_retry_reexecution_tagged_and_split_out(tmp_path):
    s = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "1",
                    "spark.rapids.sql.trace.enabled": "true",
                    "spark.rapids.sql.trace.path": str(tmp_path)})
    t = pa.table({"k": ["a", "b"] * 32, "v": list(range(64))})
    got = s.create_dataframe(t).group_by("k") \
        .agg(F.sum(col("v"))).collect().to_pylist()
    assert sorted(r["k"] for r in got) == ["a", "b"]
    # task rollups report attempt count AND the replayed-attempt time
    # separately from the exec timers (first-attempt = timer - wasted)
    recs = _task_rollups(s.last_trace_paths)
    assert any(r["metrics"].get("retryCount", 0) >= 1 for r in recs)
    assert any(r["metrics"].get("retryWastedTime", 0) > 0 for r in recs)
    events = PR.validate_chrome_trace(s.last_trace_paths["trace"])
    attempts = [e for e in events
                if e["ph"] == "X" and e["name"] == "retryAttempt"]
    assert attempts, "failed attempt must be a tagged span"
    assert attempts[0]["args"]["retried"] is True
    assert attempts[0]["args"]["attempt"] == 1
    succ = [e for e in events
            if e["ph"] == "i" and e["name"] == "retrySucceeded"]
    assert succ and succ[0]["args"]["attempts"] == 2
    # registry side: the wasted-time counter advanced
    snap = obs.state().registry.snapshot()
    assert snap["rapids_retries_total"] >= 1
    assert snap["rapids_retry_wasted_ns_total"] > 0


def test_split_retry_wasted_time_accounted(tmp_path):
    # the split flavor replays work too: its failed attempt must be a
    # tagged span and count into retryWastedTime like a plain retry
    s = TpuSession({"spark.rapids.sql.test.injectRetryOOM": "1,0,split",
                    "spark.rapids.sql.trace.enabled": "true",
                    "spark.rapids.sql.trace.path": str(tmp_path)})
    t = pa.table({"k": ["a", "b"] * 32, "v": list(range(64))})
    got = s.create_dataframe(t).group_by("k") \
        .agg(F.sum(col("v"))).collect().to_pylist()
    assert sorted(r["k"] for r in got) == ["a", "b"]
    recs = _task_rollups(s.last_trace_paths)
    assert any(r["metrics"].get("splitAndRetryCount", 0) >= 1
               for r in recs)
    assert any(r["metrics"].get("retryWastedTime", 0) > 0 for r in recs)
    events = PR.validate_chrome_trace(s.last_trace_paths["trace"])
    attempts = [e for e in events
                if e["ph"] == "X" and e["name"] == "retryAttempt"]
    assert attempts and attempts[0]["args"].get("split") is True


def test_semaphore_hold_time_accumulates(tmp_path):
    s = TpuSession({"spark.rapids.sql.trace.enabled": "true",
                    "spark.rapids.sql.trace.path": str(tmp_path)})
    _query(s)
    recs = _task_rollups(s.last_trace_paths)
    assert any(r["metrics"].get("semaphoreHoldTime", 0) > 0
               for r in recs), recs


def test_serialized_shuffle_bytes_metric(tmp_path):
    # historyDir makes obs a rollup consumer, so the registry counter
    # must mirror the exchange's GpuMetric
    s = TpuSession({"spark.rapids.shuffle.mode": "SERIALIZED",
                    "spark.rapids.obs.historyDir": str(tmp_path)})
    t = _table(3000)
    (s.create_dataframe(t, num_partitions=3)
     .group_by("k").agg(F.sum(col("v"))).collect())
    written = sum(snap.get("shuffleBytesWritten", 0)
                  for snap in s.last_metrics().values())
    assert written > 0
    snap = obs.state().registry.snapshot()
    assert snap["rapids_shuffle_bytes_written_total"] == written


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE
# ---------------------------------------------------------------------------

def test_explain_analyze_matches_last_metrics(capsys):
    from spark_rapids_tpu.runtime.metrics import exec_rollup
    s = TpuSession({"spark.rapids.sql.reader.batchSizeRows": "1024"})
    df = (s.create_dataframe(_table(8000), num_partitions=1)
          .filter(col("v") > lit(5))
          .select(col("k"), (col("v") + lit(1)).alias("v1"), col("d"))
          .filter(col("d") < lit(0.95))
          .select(col("k"), (col("v1") * lit(3)).alias("v3"))
          .group_by("k").agg(F.sum(col("v3")).alias("s3")))
    text = df.explain(mode="analyze")
    capsys.readouterr()
    snaps = s.last_metrics()
    assert snaps, "analyze must execute the query"
    # every annotated line's numbers must match last_metrics exactly
    lines = text.splitlines()
    assert len(lines) >= len(snaps)
    i = 0
    for key, snap in snaps.items():
        r = exec_rollup(snap)
        cls = key.split("#", 1)[0]
        line = lines[i]
        assert cls in line, (key, line)
        assert f"rows={r['rows']}" in line, (key, line)
        assert f"batches={r['batches']}" in line, (key, line)
        if r["dispatches"]:
            assert f"dispatches={r['dispatches']}" in line, (key, line)
        assert f"time={r['time_ns'] / 1e6:.3f}ms" in line, (key, line)
        i += 1
    # the fused scan->filter->project chain shows real numbers
    assert "*(" in text  # fusion-group marker
    scan = [ln for ln in lines if "InMemoryScanExec" in ln]
    assert scan and "rows=8000" in scan[0]


def test_explain_analyze_without_action():
    s = TpuSession()
    assert "no executed plan" in s.explain_analyze()


def test_fusion_groups_export():
    from spark_rapids_tpu.exec.stage_fusion import fusion_groups
    s = TpuSession()
    (s.create_dataframe(_table(), num_partitions=1)
     .filter(col("v") > lit(5))
     .select(col("k"), (col("v") + lit(1)).alias("v1"))
     .filter(col("v1") < lit(1900))
     .select((col("v1") * lit(2)).alias("v2"))
     .collect())
    groups = fusion_groups(s._last_exec)
    assert groups, "expected at least one fused stage"
    g = groups[0]
    assert g["kind"] in ("fused", "absorbed")
    assert len(g["members"]) >= 2 and g["stage_id"] is not None


# ---------------------------------------------------------------------------
# history server + profiler report cross-link
# ---------------------------------------------------------------------------

def test_history_server_renders_diffable_pair(tmp_path):
    import history_server as HS
    hist = tmp_path / "hist"
    s = TpuSession({"spark.rapids.obs.historyDir": str(hist)})
    _query(s)
    _query(s)  # same digest: a diffable pair
    s.create_dataframe(_table()).filter(col("v") > lit(0)).collect()
    out = tmp_path / "html"
    written = HS.render_site(str(hist), str(out))
    assert "index.html" in written
    diffs = [n for n in written if n.startswith("diff_")]
    assert len(diffs) == 1, "two runs of one digest -> one diff page"
    idx = open(written["index.html"]).read()
    assert idx.count("query_") >= 3
    qpages = [n for n in written if n.startswith("query_")]
    assert len(qpages) == 3
    body = open(written[qpages[0]]).read()
    for frag in ("Annotated plan", "rows=", "time="):
        assert frag in body, frag
    diff_body = open(written[diffs[0]]).read()
    assert "→" in diff_body and "Δ time" in diff_body


def test_history_server_marks_failures_and_fallbacks(tmp_path):
    hist = tmp_path / "hist"
    s = TpuSession({"spark.rapids.obs.historyDir": str(hist),
                    "spark.sql.ansi.enabled": "true"})
    t = pa.table({"v": [1, 2], "z": [1, 0]})
    with pytest.raises(SparkException):
        s.create_dataframe(t).select((col("v") / col("z")).alias("x")) \
            .collect()
    import history_server as HS
    written = HS.render_site(str(hist), str(tmp_path / "html"))
    idx = open(written["index.html"]).read()
    assert "failed" in idx
    qpage = [p for n, p in written.items() if n.startswith("query_")][0]
    assert "SparkException" in open(qpage).read()


def test_profiler_report_history_cross_link(tmp_path):
    s = TpuSession({
        "spark.rapids.obs.historyDir": str(tmp_path / "hist"),
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.path": str(tmp_path / "tr")})
    _query(s)
    art = PR.load_artifacts(s.last_trace_paths["trace"])
    rec = PR.cross_link_history(art, str(tmp_path / "hist"))
    assert rec is not None
    # the trace and the history record resolve to the SAME query: shared
    # digest AND the record points back at this very trace file
    assert rec["plan_digest"] == art["query"]["plan_digest"]
    assert os.path.abspath(rec["trace_paths"]["trace"]) == \
        os.path.abspath(s.last_trace_paths["trace"])
    report = PR.generate_report(art, history_rec=rec)
    assert "History cross-link" in report


def test_nds_scorecard_history_round_trip(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "nds_probe", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "nds_probe.py"))
    nds = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(nds)
    s = TpuSession()
    plan = s.create_dataframe(_table()).filter(col("v") > lit(1)).plan
    nds.append_scorecard(str(tmp_path), 5,
                         {"status": "ok", "device": "clean",
                          "rows": 10, "seconds": 0.5}, plan, time.time(),
                         sf=0.01)
    nds.append_scorecard(str(tmp_path), 5,
                         {"status": "ok", "device": "clean",
                          "rows": 10, "seconds": 0.4}, plan, time.time(),
                         sf=0.01)
    # a failure record at the same sf, later: latest run wins means the
    # regression shows; a different sf must NOT leak into the summary
    nds.append_scorecard(str(tmp_path), 7, {"status": "error",
                                            "error": "boom"},
                         None, time.time(), sf=0.01)
    nds.append_scorecard(str(tmp_path), 9, {"status": "ok", "rows": 1,
                                            "seconds": 9.9},
                         None, time.time(), sf=1.0)
    summary = nds.scorecard_from_history(str(tmp_path), sf=0.01)
    assert summary["translated"] == 2 and summary["ok"] == 1
    assert summary["queries"]["q5"]["seconds"] == 0.4  # latest run wins
    assert summary["queries"]["q7"]["status"] == "error"
    assert summary["queries"]["q9"] == {"status": "not_translated"}
    assert summary["queries"]["q1"] == {"status": "not_translated"}


def test_healthz_endpoint_free_port_scrape_via_urllib():
    # regression: the endpoint must bind 127.0.0.1 only and answer 404
    # for unknown paths
    port = obs_smoke._free_port()
    TpuSession({"spark.rapids.obs.port": str(port)})
    code, _ = obs_smoke._get(f"http://127.0.0.1:{port}/nope")
    assert code == 404
    req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
    with urllib.request.urlopen(req, timeout=5) as r:
        assert "text/plain" in r.headers["Content-Type"]
