"""Sort, string and datetime expression tests plus fallback assertions
(reference sort_test.py / string_test.py / date_time_test.py and
assert_gpu_fallback_collect)."""
import datetime

import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.plan.nodes import SortOrder

from asserts import assert_tpu_and_cpu_are_equal_collect, assert_fallback_collect


@pytest.fixture
def session():
    return TpuSession()


DATA = {
    "a": pa.array([5, None, 1, 3, 3, None, 2, 8], pa.int64()),
    "f": pa.array([1.5, float("nan"), None, -0.0, 0.0, 2.5, -3.5, None]),
    "s": pa.array(["banana", "Apple", None, "", "cherry", "apple", "date", "b"]),
    "d": pa.array([datetime.date(2024, 1, 15), datetime.date(1999, 12, 31),
                   None, datetime.date(2024, 2, 29), datetime.date(1970, 1, 1),
                   datetime.date(2038, 7, 4), datetime.date(2024, 1, 15),
                   datetime.date(1969, 7, 20)]),
    "ts": pa.array([datetime.datetime(2024, 1, 15, 10, 30, 45),
                    datetime.datetime(1999, 12, 31, 23, 59, 59), None,
                    datetime.datetime(2024, 2, 29, 0, 0, 1),
                    datetime.datetime(1970, 1, 1, 0, 0, 0),
                    datetime.datetime(2038, 7, 4, 12, 0, 0),
                    datetime.datetime(2024, 1, 15, 18, 45, 0),
                    datetime.datetime(1969, 7, 20, 20, 17, 40)],
                   pa.timestamp("us")),
}


def make_df(s, parts=1):
    return s.create_dataframe(dict(DATA), num_partitions=parts)


# -- sort -------------------------------------------------------------------

@pytest.mark.parametrize("asc", [True, False])
def test_sort_int(session, asc):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).order_by(SortOrder(col("a"), ascending=asc)),
        session)


@pytest.mark.parametrize("asc", [True, False])
@pytest.mark.parametrize("nulls_first", [True, False])
def test_sort_float_nan(session, asc, nulls_first):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(col("f"), col("a")).order_by(
            SortOrder(col("f"), ascending=asc, nulls_first=nulls_first),
            SortOrder(col("a"))),
        session)


def test_sort_multi_key(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s, 2).order_by(
            SortOrder(col("a"), ascending=True),
            SortOrder(col("f"), ascending=False)),
        session)


def test_sort_date(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(col("d")).order_by(SortOrder(col("d"))),
        session)


def test_sort_string_on_device(session):
    """String ORDER BY runs on DEVICE via exact 8-byte chunk keys
    (kernels.string_chunk_keys) — no fallback, results exact."""
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.exec import tpu_nodes as X
    df = make_df(session).select(col("s")).order_by(SortOrder(col("s")))
    root, _ = convert_plan(df.plan, session.conf)
    assert isinstance(root, X.SortExec)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(col("s")).order_by(SortOrder(col("s"))),
        session)


# -- strings ----------------------------------------------------------------

def test_string_length_case(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            F.length(col("s")).alias("len"), F.upper(col("s")).alias("up"),
            F.lower(col("s")).alias("lo")),
        session)


def test_string_substring(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            F.substring(col("s"), 1, 3).alias("s13"),
            F.substring(col("s"), 2, 2).alias("s22"),
            F.substring(col("s"), -3, 2).alias("sm3")),
        session)


def test_string_concat(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            F.concat(col("s"), lit("_x"), col("s")).alias("c")),
        session)


def test_string_predicates(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            F.startswith(col("s"), "a").alias("sw"),
            F.endswith(col("s"), "e").alias("ew"),
            F.contains(col("s"), "an").alias("ct"),
            (col("s") == lit("apple")).alias("eq")),
        session)


def test_like_transpiled(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            F.like(col("s"), "a%").alias("p1"),
            F.like(col("s"), "%e").alias("p2"),
            F.like(col("s"), "%an%").alias("p3"),
            F.like(col("s"), "a%e").alias("p4"),
            F.like(col("s"), "apple").alias("p5")),
        session)


def test_like_underscore_on_device(session):
    # '_' wildcards now compile to the device NFA (upgrade over the simple
    # starts/ends/contains transpile)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(F.like(col("s"), "a_b%c").alias("p")),
        session)


def test_like_complex_falls_back(session):
    # non-ASCII literal + '_' needs the NFA, which rejects non-ASCII
    assert_fallback_collect(
        lambda s: make_df(s).select(F.like(col("s"), "a_日%").alias("p")),
        session, "Project")


def test_string_group_key_unicode(session):
    data = {"k": ["héllo", "wörld", "héllo", "日本語", None, "日本語"],
            "v": [1, 2, 3, 4, 5, 6]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data).group_by(col("k")).agg(
            F.sum("v").alias("sv")),
        session, ignore_order=True)


def test_utf8_length(session):
    data = {"s": ["héllo", "日本語", "a🚀b", ""]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data).select(
            F.length(col("s")).alias("n"),
            F.substring(col("s"), 2, 2).alias("sub")),
        session)


def test_cast_int_string_roundtrip(session):
    from spark_rapids_tpu import types as T
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            col("a").cast(T.STRING).alias("astr"),
            col("a").cast(T.STRING).cast(T.INT64).alias("aint")),
        session)


def test_cast_string_to_int(session):
    from spark_rapids_tpu import types as T
    data = {"s": ["42", " -7 ", "abc", "", "123456789012", None, "+5", "1.5"]}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(data).select(
            col("s").cast(T.INT64).alias("v")),
        session)


# -- datetime ---------------------------------------------------------------

def test_date_parts(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            F.year(col("d")), F.month(col("d")), F.dayofmonth(col("d")),
            F.dayofweek(col("d")), F.last_day(col("d")).alias("ld")),
        session)


def test_timestamp_parts(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            F.year(col("ts")), F.month(col("ts")), F.dayofmonth(col("ts")),
            F.hour(col("ts")), F.minute(col("ts")), F.second(col("ts"))),
        session)


def test_date_arithmetic(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            F.date_add(col("d"), lit(30)).alias("plus30"),
            F.date_sub(col("d"), lit(45)).alias("minus45"),
            F.datediff(col("d"), lit(datetime.date(2000, 1, 1))).alias("dd")),
        session)


def test_date_group_key(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).group_by(col("d")).agg(F.count().alias("c")),
        session, ignore_order=True)


def test_ts_cast_date(session):
    from spark_rapids_tpu import types as T
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: make_df(s).select(
            col("ts").cast(T.DATE).alias("d2"),
            col("d").cast(T.TIMESTAMP).alias("ts2")),
        session)


# -- explain ----------------------------------------------------------------

def test_explain_reports_fallback(session):
    from spark_rapids_tpu.plan.overrides import explain_plan
    from spark_rapids_tpu.sql import functions as _F
    # alternation is outside the tagged device-NFA subset -> CPU fallback
    df = make_df(session).select(_F.regexp_extract(col("s"), "(a+|b)x", 1)
                                 .alias("m"))
    text = explain_plan(df.plan, session.conf, all_ops=True)
    assert "cannot run on TPU because" in text
    assert "reject strategy" in text


def test_exec_disable_conf(session):
    from spark_rapids_tpu.sql.session import TpuSession
    s2 = TpuSession({"spark.rapids.sql.exec.Filter": "false"})
    assert_fallback_collect(
        lambda s: make_df(s).filter(col("a") > lit(2)), s2, "Filter")


def test_device_string_sort_exact(session):
    # unicode, shared prefixes, >8-byte strings, empties, nulls — exact
    # lexicographic byte order on device, asc and desc
    import pyarrow as pa
    vals = ["pear", "Peach", "", None, "apple", "applesauce", "appl",
            "züricher-strasse-123456789", "zürich", "éclair", "é",
            "aaaaaaaabbbbbbbbcccccccc", "aaaaaaaabbbbbbbbcccccccd", None,
            "z", "a" * 40, "a" * 39]
    t = {"s": pa.array(vals), "i": pa.array(list(range(len(vals))))}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).order_by(
            SortOrder(col("s"), ascending=True, nulls_first=False), col("i").asc()),
        session)
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).order_by(
            SortOrder(col("s"), ascending=False), col("i").asc()),
        session)


def test_device_string_sort_generated(session):
    from data_gen import StringGen, IntegerGen, gen_df
    spec = [("s", StringGen(min_len=0, max_len=25)), ("i", IntegerGen())]
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: gen_df(s, spec, length=2048, seed=91).order_by(
            SortOrder(col("s")), col("i").asc()),
        session)


def test_device_string_sort_ooc(session):
    # out-of-core path with string keys (chunk widths differ per batch)
    import pyarrow as pa
    from spark_rapids_tpu.sql.session import TpuSession
    s2 = TpuSession({"spark.rapids.sql.sort.outOfCoreBytes": 1})
    vals = ["kiwi", "banana", None, "apple", "fig", "cherry" * 5, "date"]
    t = {"s": pa.array(vals)}
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t, num_partitions=1).order_by(
            SortOrder(col("s"))),
        s2)
