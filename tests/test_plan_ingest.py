"""Plan-ingestion contract tests (the Spark boundary seam;
docs/architecture.md L2 re-scope)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.plan.ingest import ingest
from spark_rapids_tpu.expr.core import SparkException

from asserts import assert_tables_equal


@pytest.fixture
def session():
    return TpuSession()


def _run_both(doc, session):
    df = ingest(doc, session)
    tpu = df.collect()
    cpu = df.collect_cpu()
    assert_tables_equal(tpu, cpu, ignore_order=True)
    return tpu.to_pylist()


def test_ingest_q6_shaped(session, tmp_path):
    import pyarrow.parquet as pq
    path = str(tmp_path / "li.parquet")
    pq.write_table(pa.table({
        "qty": pa.array([10.0, 30.0, 5.0, 20.0]),
        "price": pa.array([100.0, 200.0, 300.0, 400.0]),
        "disc": pa.array([0.05, 0.06, 0.02, 0.07])}), path)
    doc = {"version": 1, "plan": {
        "node": "aggregate", "keys": [],
        "aggs": [{"fn": "sum", "alias": "rev",
                  "child": {"expr": "mul",
                            "left": {"expr": "col", "name": "price"},
                            "right": {"expr": "col", "name": "disc"}}}],
        "child": {"node": "filter",
                  "condition": {"expr": "and",
                                "left": {"expr": "ge",
                                         "left": {"expr": "col", "name": "disc"},
                                         "right": {"expr": "lit", "value": 0.05}},
                                "right": {"expr": "lt",
                                          "left": {"expr": "col", "name": "qty"},
                                          "right": {"expr": "lit", "value": 24.0}}},
                  "child": {"node": "parquet_scan", "paths": [path]}}}}
    rows = _run_both(doc, session)
    assert abs(rows[0]["rev"] - (100 * 0.05 + 400 * 0.07)) < 1e-9


def test_ingest_join_sort_limit(session):
    doc = {"version": 1, "plan": {
        "node": "limit", "n": 3,
        "child": {"node": "sort",
                  "orders": [{"expr": {"expr": "col", "name": "v"},
                              "ascending": False}],
                  "child": {"node": "join", "how": "inner",
                            "left_keys": [{"expr": "col", "name": "k"}],
                            "right_keys": [{"expr": "col", "name": "k"}],
                            "left": {"node": "in_memory",
                                     "rows": {"k": [1, 2, 3, 4],
                                              "v": [10, 20, 30, 40]}},
                            "right": {"node": "in_memory",
                                      "rows": {"k": [2, 3, 4, 5]}}}}}}
    rows = ingest(doc, session).collect().to_pylist()
    assert [r["v"] for r in rows] == [40, 30, 20]


def test_ingest_generate_and_calls(session):
    doc = {"version": 1, "plan": {
        "node": "generate", "generator": "explode",
        "input": {"expr": "call", "fn": "sequence",
                  "args": [{"expr": "lit", "value": 1},
                           {"expr": "col", "name": "n"}]},
        "child": {"node": "in_memory", "rows": {"n": [2, 3]}}}}
    rows = _run_both(doc, session)
    assert sorted(r["col"] for r in rows) == [1, 1, 2, 2, 3]


def test_ingest_version_gate(session):
    with pytest.raises(SparkException, match="version"):
        ingest({"version": 99, "plan": {}}, session)
