"""Differential tests for the second-tier expression breadth: extended
math, datetime unit conversions, string length/slice family, hashes,
collection constructors (VERDICT r3 #1: registry breadth)."""
import datetime as dtm

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

from asserts import assert_tpu_and_cpu_are_equal_collect


@pytest.fixture
def session():
    return TpuSession()


def _num_tbl(n=80, seed=21):
    rng = np.random.default_rng(seed)
    return pa.table({
        "f": pa.array(np.round(rng.uniform(-100, 100, n), 3)),
        "g": pa.array(np.round(rng.uniform(0.1, 50, n), 3)),
        "i": pa.array(rng.integers(-1000, 1000, n).astype(np.int64)),
        "j": pa.array(rng.integers(0, 25, n).astype(np.int32)),
        "p": pa.array(rng.integers(0, 64, n).astype(np.int32)),
    })


def test_math_extended_unary(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_num_tbl()).select(
            F.cbrt(col("f")).alias("cb"),
            F.cot(col("g")).alias("ct"),
            F.sec(col("g")).alias("se"),
            F.csc(col("g")).alias("cs"),
            F.degrees(col("f")).alias("dg"),
            F.radians(col("f")).alias("rd"),
            F.expm1(col("g") / lit(50.0)).alias("em"),
            F.log1p(col("g")).alias("lp"),
            F.rint(col("f")).alias("ri")),
        session, approx_float=1e-12)


def test_math_binary_and_bits(session):
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(_num_tbl()).select(
            F.hypot(col("f"), col("g")).alias("hy"),
            F.nanvl(col("f") / (col("f") - col("f")), col("g")).alias("nv"),
            F.factorial(col("j")).alias("fa"),
            F.bit_count(col("i")).alias("bc"),
            F.getbit(col("i"), col("p")).alias("gb"),
            F.bround(col("f"), 1).alias("br"),
            F.bround(col("i"), -2).alias("bri")),
        session, approx_float=1e-12)


def test_datetime_conversions(session):
    rng = np.random.default_rng(3)
    n = 60
    t = pa.table({
        "d": pa.array(rng.integers(-20000, 20000, n).astype(np.int32),
                      pa.date32()),
        "ts": pa.array(rng.integers(-2_000_000_000, 2_000_000_000, n)
                       * 1000, pa.timestamp("us")),
        "ms": pa.array(rng.integers(-10**12, 10**12, n)),
        "y": pa.array(rng.integers(1, 3000, n).astype(np.int32)),
        "m": pa.array(rng.integers(0, 14, n).astype(np.int32)),
        "dd": pa.array(rng.integers(0, 33, n).astype(np.int32)),
    })
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.unix_date(col("d")).alias("ud"),
            F.date_from_unix_date(F.unix_date(col("d"))).alias("rt"),
            F.unix_micros(col("ts")).alias("um"),
            F.unix_millis(col("ts")).alias("ul"),
            F.unix_seconds(col("ts")).alias("us"),
            F.timestamp_millis(col("ms")).alias("tm"),
            F.timestamp_micros(col("ms")).alias("tu"),
            F.make_date(col("y"), col("m"), col("dd")).alias("md"),
            F.next_day(col("d"), "Mon").alias("nd"),
            F.months_between(col("ts"), col("ts")).alias("mb0")),
        session)


def test_months_between_values(session):
    t = pa.table({"e": pa.array([dtm.datetime(2024, 3, 31), dtm.datetime(2024, 2, 29),
                                 dtm.datetime(2024, 7, 15, 12, 0), None],
                                pa.timestamp("us")),
                  "s": pa.array([dtm.datetime(2024, 2, 29), dtm.datetime(2023, 2, 28),
                                 dtm.datetime(2024, 5, 10, 6, 30),
                                 dtm.datetime(2024, 1, 1)], pa.timestamp("us"))})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.months_between(col("e"), col("s")).alias("mb")),
        session, approx_float=1e-9)


def test_string_lengths_and_slices(session):
    t = pa.table({"s": pa.array(["hello", "", "héllo wörld", None, "日本語",
                                 "x", "padded   ", "ab"]),
                  "n": pa.array([1, 2, 3, 4, 0, -1, 2, 5],
                                type=pa.int32())})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.octet_length(col("s")).alias("ol"),
            F.bit_length(col("s")).alias("bl"),
            F.left(col("s"), 3).alias("lf"),
            F.right(col("s"), 2).alias("rt"),
            F.chr_(col("n") + lit(64)).alias("ch")),
        session)


def test_cpu_tier_string_functions(session):
    t = pa.table({"s": pa.array(["abc", "b,a,c", "hello", None, "Robert"]),
                  "t": pa.array(["abd", "a,b,c", "hola", "x", "Rupert"])})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.find_in_set(col("s"), col("t")).alias("fis"),
            F.levenshtein(col("s"), col("t")).alias("lv"),
            F.base64(col("s")).alias("b64"),
            F.unbase64(F.base64(col("s"))).alias("rt64"),
            F.soundex(col("s")).alias("sx"),
            F.format_string("%s/%s", col("s"), col("t")).alias("fs"),
            F.elt(lit(2), col("s"), col("t")).alias("el")),
        session)


def test_hashes(session):
    rng = np.random.default_rng(8)
    t = pa.table({"s": pa.array(["", "a", "abc", None, "hello world",
                                 "The quick brown fox"] * 5),
                  "i": pa.array(rng.integers(-10**9, 10**9, 30)),
                  "f": pa.array(rng.uniform(-5, 5, 30))})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.crc32(col("s")).alias("crc"),
            F.xxhash64(col("i"), col("f")).alias("xx")),
        session)


def test_crc32_known_values(session):
    # independently-known CRC32 vectors
    import zlib
    t = pa.table({"s": pa.array(["", "a", "123456789", "hello"])})
    out = session.create_dataframe(t).select(
        F.crc32(col("s")).alias("c")).to_pydict()
    assert out["c"] == [zlib.crc32(x.encode()) for x in
                        ["", "a", "123456789", "hello"]]


def test_collection_constructors(session):
    rows_a = [[1, 2], [], None, [5, None, 7]]
    rows_b = [[9], [8, 7], [1], [2, 3]]
    maps = [[(1, 10)], [(2, 20), (3, 30)], None, [(4, None)]]
    t = pa.table({
        "a": pa.array(rows_a, pa.list_(pa.int64())),
        "b": pa.array(rows_b, pa.list_(pa.int64())),
        "m": pa.array(maps, pa.map_(pa.int64(), pa.int64())),
        "v": pa.array([7, 8, None, 9], pa.int64()),
        "n": pa.array([2, 0, 3, -1], pa.int32()),
        "s": pa.array(["a:1,b:2", "x:9", None, "k:"]),
    })
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.array_repeat(col("v"), col("n")).alias("ar"),
            F.array_join(col("a"), "-", "NULL").alias("aj"),
            F.map_entries(col("m")).alias("me"),
            F.map_from_arrays(col("b"), col("b")).alias("mfa"),
            F.str_to_map(col("s")).alias("stm")),
        session)


def test_arrays_zip_and_map_concat(session):
    t = pa.table({
        "a": pa.array([[1, 2], [3]], pa.list_(pa.int64())),
        "b": pa.array([[9], [8, 7]], pa.list_(pa.int64())),
        "m1": pa.array([[(1, 10)], [(2, 20)]],
                       pa.map_(pa.int64(), pa.int64())),
        "m2": pa.array([[(5, 50)], [(6, 60)]],
                       pa.map_(pa.int64(), pa.int64())),
    })
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.arrays_zip(col("a"), col("b")).alias("z"),
            F.map_concat(col("m1"), col("m2")).alias("mc")),
        session)


def test_json_tuple(session):
    t = pa.table({"j": pa.array(
        ['{"a": 1, "b": "x"}', '{"a": null}', "not json", None,
         '{"b": {"c": 2}}'])})
    assert_tpu_and_cpu_are_equal_collect(
        lambda s: s.create_dataframe(t).select(
            F.json_tuple(col("j"), "a", "b").alias("jt")),
        session)
